"""Fused bitwise decode ops for the compact host→device wire.

The host→device link is the training pipeline's scarce resource (the
recorded link-bound ceiling sits at 34-69k examples/sec against a
0.6-1.3M device-only rate), so the ingest prep stage ships *encoded*
batch buffers (learner/wire.py) and the jitted train step reconstructs
the original arrays with the ops in this module — decoded batches never
cross the link. Every op here is trace-pure (pslint jit-purity pass):
pure jnp on traced operands, shapes static, no host effects.

Encodings decoded here (host encoders in learner/wire.py +
utils/bitpack.py):

- ``decode_u24``            3-byte little-endian slot ids → int32
- ``decode_bitstream``      ceil(log2 S)-bit packed ids → int32
- ``decode_sign_labels``    1-bit labels → ±1 float32 (0 past ``count``)
- ``decode_mask``           live-row count → {0,1} float32 row mask
- ``decode_row_ids``        per-row feature counts → COO row-id array
- ``decode_sorted_deltas``  u16 gap stream → sorted unique slot array
- ``decode_binary_vals``    nnz count → the all-ones value array
- ``decode_fixed_point``    u8/u16 codes + per-shard (lo, hi) → float32
- ``decode_bf16``           bfloat16 values → float32
- ``decode_stream_slots``   lane-dictionary wire (per-lane ``uslots``
  tables + packed ``ucols`` + raw-lane bitstream) → ELL slot matrix

Each is the exact inverse of its host encoder over the encoder's
declared domain (the encoder VERIFIES the domain per batch and falls
back to the raw wire otherwise), so the default ``exact`` wire decodes
bit-identical to the unencoded stream — parity-tested in
tests/test_wire.py.
"""
# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import jax.numpy as jnp

from ..filter.fixing_float import dequantize_jax
from ..utils.bitpack import unpack_bits, unpack_sign_bits


def decode_u24(b: jnp.ndarray) -> jnp.ndarray:
    """uint8 [.., 3] little-endian → int32 [..] (inverse of
    async_sgd.pack_u24): three cheap VPU ops, no gather."""
    s = b.astype(jnp.int32)
    return s[..., 0] | (s[..., 1] << 8) | (s[..., 2] << 16)


def decode_bitstream(words: jnp.ndarray, n: int, bits: int) -> jnp.ndarray:
    """uint32 word stream → int32 [n]: the ceil(log2 S)-bit slot wire
    (utils/bitpack.unpack_bits — tiled gather-free form when n divides
    the value period, two-gather fallback otherwise)."""
    return unpack_bits(words, n, bits)


def decode_sign_labels(y_bits: jnp.ndarray, count, rows: int) -> jnp.ndarray:
    """1-bit label stream → float32 [rows] of ±1, exactly 0.0 on padding
    rows (the raw wire stores literal 0.0 there, and exact-mode decode
    must reproduce it bit-for-bit)."""
    y = unpack_sign_bits(y_bits, rows)
    return jnp.where(jnp.arange(rows) < count, y, 0.0)


def decode_mask(count, rows: int) -> jnp.ndarray:
    """Live-row count → the float32 {1.0, 0.0} row mask (the raw wire's
    ``mask`` is always ``1.0[:n]`` by construction — prep_batch*)."""
    return (jnp.arange(rows) < count).astype(jnp.float32)


def decode_row_ids(row_counts: jnp.ndarray, nnz, nnz_pad: int) -> jnp.ndarray:
    """Per-row feature counts (uint8/uint16 [R]) → int32 [nnz_pad] COO
    row-id array ``repeat(arange(R), counts)`` zero-padded past ``nnz``.

    Scatter-free-of-gathers reconstruction: drop a +1 marker at each
    row's start offset (rows with zero features stack their markers on
    the next start — the cumsum then jumps by their count, skipping
    them exactly like np.repeat does), inclusive-cumsum, and mask the
    padding tail back to the raw wire's literal zeros.
    """
    starts = jnp.cumsum(row_counts.astype(jnp.int32))[:-1]  # rows 1..R-1
    bumps = (
        jnp.zeros((nnz_pad,), jnp.int32)
        .at[starts]
        .add(1, mode="drop")  # a trailing all-empty tail lands at nnz
    )
    ids = jnp.cumsum(bumps)
    return jnp.where(jnp.arange(nnz_pad) < nnz, ids, 0)


def decode_sorted_deltas(
    deltas: jnp.ndarray, n_uniq, sentinel: int
) -> jnp.ndarray:
    """u16 gap stream → sorted int32 slot array, ``sentinel`` past
    ``n_uniq`` (the exact wire's ``uslots`` layout: np.unique output is
    strictly increasing, so gaps are ≥1 and — verified per batch by the
    host encoder — fit u16; element 0 carries the absolute first slot).
    The cumsum runs in int32, so reconstruction is exact."""
    s = jnp.cumsum(deltas.astype(jnp.int32))
    return jnp.where(jnp.arange(deltas.shape[0]) < n_uniq, s, sentinel)


def decode_binary_vals(nnz, nnz_pad: int) -> jnp.ndarray:
    """nnz count → the float32 value array of a binary batch: exactly
    1.0 on live entries, exactly 0.0 on padding — what prep_batch*
    writes for ``batch.binary`` data, elided from the wire entirely."""
    return (jnp.arange(nnz_pad) < nnz).astype(jnp.float32)


def decode_fixed_point(q: jnp.ndarray, lo, hi, num_bytes: int) -> jnp.ndarray:
    """u8/u16 fixed-point codes + per-shard scalar (lo, hi) → float32
    (the quantized value wire; filter/fixing_float dequantize_jax)."""
    return dequantize_jax(q, lo, hi, num_bytes)


def decode_bf16(v: jnp.ndarray) -> jnp.ndarray:
    """bfloat16 value stream → float32 (widening is exact)."""
    return v.astype(jnp.float32)


def decode_stream_slots(
    raw_words: jnp.ndarray,
    code_words: jnp.ndarray,
    table_words: jnp.ndarray,
    lane_starts: jnp.ndarray,
    *,
    rows: int,
    lanes: int,
    dict_lanes: tuple,
    code_bits: int,
    dict_pad: int,
    raw_bits: int,
) -> jnp.ndarray:
    """Stream-once lane-dictionary wire → the int32 [rows, lanes] ELL
    slot matrix (learner/wire.EncodedEllStreamBatch's host encode,
    inverted on device).

    Dictionary lanes decode as ``uslots[lane_start + ucol]``: unpack
    the ``code_bits``-wide ucol stream, add each dict lane's static
    table offset, gather from the unpacked ``uslots`` table; raw lanes
    unpack straight from the ``raw_bits`` stream. The static lane split
    then interleaves both column groups back into original lane order
    with one compile-time permutation (a free layout choice for XLA).

    Garbage on PADDING rows is in-bounds by construction — codes are
    ``code_bits`` wide and the clamp keeps ``start + code`` inside the
    power-of-two ``dict_pad`` table, whose dead entries are packed
    zeros (slot 0) — and every padding row's contribution is gated by
    the row mask inside the step, exactly like the bits wire."""
    n_dict = len(dict_lanes)
    n_raw = lanes - n_dict
    parts = []
    if n_dict:
        table = unpack_bits(table_words, dict_pad, raw_bits)
        ucols = unpack_bits(code_words, rows * n_dict, code_bits).reshape(
            rows, n_dict
        )
        idx = jnp.minimum(lane_starts[None, :] + ucols, dict_pad - 1)
        parts.append(table[idx])
    if n_raw:
        parts.append(
            unpack_bits(raw_words, rows * n_raw, raw_bits).reshape(
                rows, n_raw
            )
        )
    cols = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
    # static inverse permutation: concat order is (dict lanes in lane
    # order, then raw lanes in lane order) → original lane order
    dict_set = frozenset(dict_lanes)
    concat_order = list(dict_lanes) + [
        j for j in range(lanes) if j not in dict_set
    ]
    perm = [0] * lanes
    for pos, j in enumerate(concat_order):
        perm[j] = pos
    return cols[:, tuple(perm)]
