"""Fused sparse FTRL-proximal update — Pallas TPU gather→update→scatter.

The big-table row path (``update='sparse'``, updaters.apply_state_rows)
today runs as four separate XLA ops — gather z, gather √n, scatter z',
scatter √n' — each a full trip through the memory system with
intermediate row vectors materialized between them (~80 ms for 640k
rows at 2^30 slots, 0.7–1.5% of HBM peak per BENCH_r05/BENCH_ONCHIP).
This kernel is the IO-aware formulation (the FlashAttention lesson,
arXiv:2205.14135): ONE pass over exactly the touched rows —

- the deduped slot ids are reduced to unique 128-lane TABLE ROWS and
  scalar-prefetched (``PrefetchScalarGridSpec``), so the kernel can
  issue row DMAs before any tensor work runs;
- each grid block DMAs its rows HBM→VMEM double-buffered (block b+1's
  fetches are in flight while block b computes — the grid is
  sequential, scratch persists across steps);
- the FTRL-proximal step (``_ftrl_math`` from ops/ftrl.py — the single
  copy of the math) runs vectorized in VMEM, membership derived per
  lane as ``g != 0`` (the unquantized-push contract);
- updated rows DMA straight back to the SAME HBM buffers
  (``input_output_aliases`` — no fresh table copy, the constraint that
  lets one chip hold a 2^30-slot table), write-back overlapping the
  next block's compute.

Gradients arrive as a per-unique-row dense [U, 128] scatter (built
in-program from the deduped ``g_u`` vector): prep's slot-unique
contract makes every genuine (row, lane) target unique, padding and
non-owned entries carry g = 0 and merge into real rows as pass-through
lanes, so the kernel never needs a mask operand or a sentinel row.

``sqrt_n`` may be stored bf16 (``SGDConfig.ftrl_state_dtype``): math
widens to f32 in VMEM and the write-back narrows with STOCHASTIC
rounding — the on-core PRNG when compiled, and on the interpret path a
dither substitute indexed by each lane's u-position so the narrow is
BIT-IDENTICAL to the jnp reference's position-hash dither
(ops/ftrl.dither_hash_u32, the parity-test contract).

``ftrl_sparse_update`` auto-selects: Pallas on TPU backends for
tileable shapes, the XLA rows reference elsewhere (bit-identical
formulation of updaters.apply_state_rows for the FTRL/decay case).
"""
# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from .ftrl import (
    _LANES,
    _TILE,
    _choose_block_rows,
    _ftrl_math,
    _use_pallas,
    dither_hash_u32,
    ftrl_update,
)

#: update-path names reported by :func:`resolve_update_path` and the
#: ``ps_ftrl_update_path_total`` telemetry counter / bench records
PATH_PALLAS_SPARSE = "pallas_sparse"
PATH_PALLAS_DENSE = "pallas_dense"
PATH_XLA_ROWS = "xla_rows"
PATH_REF = "ref"


def use_sparse_kernel(p: int, u: int, bf16_n: bool, has_seed: bool,
                      force_pallas: bool) -> bool:
    """Pure path-selection predicate for the fused sparse kernel
    (testable off device): the kernel runs on TPU backends, for
    (8,128)-tileable tables, for row counts the (8-sublane) block
    machinery can tile, and — when √n is stored bf16 — only with a
    seed for the stochastic narrow. Everything else falls back to the
    XLA rows path (:func:`ftrl_sparse_rows_ref`), bit-identically.
    ``force_pallas`` pins the kernel for A/B sweeps and interpret
    tests, but never onto a shape it cannot tile or narrow correctly.
    """
    if not force_pallas and not _use_pallas():
        return False
    if p % _TILE != 0 or u < 8 or u % 8 != 0:
        return False
    if bf16_n and not has_seed:
        return False
    return True


def resolve_update_path(update_mode: str, *, on_tpu: bool, shard: int,
                        u: int, bf16_n: bool, has_seed: bool) -> str:
    """Which FTRL update path a train step with these statics will
    trace — the host-side twin of the in-jit dispatch (the decision is
    static, so the host can name it without touching the device).
    Feeds the ``ps_ftrl_update_path_total`` counter and bench records:

    - ``pallas_sparse`` — update='sparse' through the fused kernel;
    - ``xla_rows``      — update='sparse' through the XLA
      gather→apply→scatter rows path;
    - ``pallas_dense``  — dense whole-shard sweep, Pallas kernel;
    - ``ref``           — dense sweep, jnp/XLA reference path.

    ``on_tpu`` is an explicit parameter (not re-probed) so the
    resolution is a pure function of its arguments — callable from
    tests and dashboards describing a remote device's dispatch.
    ``force_pallas=True`` below is how the backend gate is replaced by
    the parameter while every SHAPE gate still applies.
    """
    from .ftrl import _TILE, xla_min_slots

    if update_mode == "sparse":
        if on_tpu and use_sparse_kernel(shard, u, bf16_n, has_seed, True):
            return PATH_PALLAS_SPARSE
        return PATH_XLA_ROWS
    # the dense resolution mirrors ops/ftrl.use_ref_path with the
    # backend probe swapped for the parameter (use_ref_path's
    # force_pallas skips its xla_min_slots gate, so it cannot be
    # reused here verbatim)
    if (
        not on_tpu
        or shard % _TILE != 0
        or (bf16_n and not has_seed)
        or shard >= xla_min_slots()
    ):
        return PATH_REF
    return PATH_PALLAS_DENSE


def ftrl_sparse_rows_ref(z, sqrt_n, rel, ok, g_u, *, alpha, beta, l1,
                         l2, seed=None):
    """XLA rows reference: the exact gather→apply→scatter formulation
    ``updaters.apply_state_rows`` runs for the FTRL/decay case, inlined
    here so kernel tests and the A/B bench can call it without an
    updater object. Gathers the ``rel`` rows, applies the JITTED
    :func:`ops.ftrl.ftrl_update` exactly as ``FTRLUpdater.apply`` does
    (same ``_ftrl_math``, same position-hash bf16 narrow; calling the
    un-jitted reference here instead would diverge in the last bit at
    EAGER call sites — XLA contracts the z-accumulator multiply-add
    under jit), scatters back with non-``ok`` entries routed
    one-past-the-end in UNSIGNED index space and dropped
    (``mode='drop'`` — the apply_state_rows sentinel contract)."""
    z_u = z[rel]
    n_u = sqrt_n[rel]
    g = jnp.where(ok, g_u, 0.0)
    z_new, n_new = ftrl_update(
        z_u, n_u, g, None, alpha=alpha, beta=beta, l1=l1, l2=l2,
        seed=seed,
    )
    oob = jnp.where(ok, rel.astype(jnp.uint32), jnp.uint32(z.shape[0]))
    return (
        z.at[oob].set(z_new.astype(z.dtype), mode="drop"),
        sqrt_n.at[oob].set(n_new.astype(sqrt_n.dtype), mode="drop"),
    )


def _row_gradient(rel, ok, g_u, u: int):
    """Unique-row decomposition of the deduped slot vector (in-program,
    O(U) elementwise/scan work — small next to the row traffic it
    organizes). The ``ok`` subsequence of ``rel`` is non-decreasing
    (localize of a sorted unique ``uslots``); non-``ok`` entries are
    clip artifacts and may land OUT of order — the ≥2^31-slot sentinel
    is -1 (``slot_sentinel``), so the padding tail clips to rel 0
    BELOW the ascending owned ids. Every non-``ok`` entry carries g=0
    and merges into whichever row group absorbs it, so each is
    remapped to the running max of the ok rows (``cummax``): the row
    sequence is monotone again and the neighbor-compare dedup can
    never emit a duplicate row — a duplicate would make the later
    block's stale fetch WRITE BACK over the genuine update (a silent
    lost update, caught in review by exactly the -1-tail shape).

    Returns ``(urows [U] int32, nrows [1] int32, g_rows [U,128] f32,
    didx [U,128] int32)`` where ``urows[:nrows]`` are the distinct
    128-lane table rows touched (filler 0 past ``nrows`` — fetch-safe,
    never written back), ``g_rows`` the per-row dense gradient (scatter
    -ADD: genuine (row, lane) targets are unique by the slot-unique
    contract, padding/non-owned entries add 0), and ``didx`` each
    lane's u-position (-1 untouched) — the dither index that makes the
    interpret-mode bf16 narrow replay the reference's position hash.
    """
    g = jnp.where(ok, g_u, 0.0).astype(jnp.float32)
    relc = rel.astype(jnp.int32)
    lane = relc % _LANES
    row = jax.lax.cummax(jnp.where(ok, relc // _LANES, 0))
    first = jnp.concatenate(
        [jnp.ones((1,), jnp.int32), (row[1:] != row[:-1]).astype(jnp.int32)]
    )
    inv = jnp.cumsum(first) - 1
    nrows = (inv[-1] + 1).reshape(1)
    urows = jnp.zeros((u,), jnp.int32).at[inv].set(row)
    g_rows = jnp.zeros((u, _LANES), jnp.float32).at[inv, lane].add(g)
    didx = (
        jnp.full((u, _LANES), -1, jnp.int32)
        .at[inv, lane]
        .max(jnp.where(ok, jnp.arange(u, dtype=jnp.int32), -1))
    )
    return urows, nrows, g_rows, didx


def _grid_params(interpret: bool):
    """Sequential-grid compiler params: the double-buffer recurrence
    (scratch slots + DMA semaphores carried across grid steps) requires
    'arbitrary' dimension semantics. Same CompilerParams /
    TPUCompilerParams compat chain as ops/flash_attention."""
    if interpret:
        return {}
    from jax.experimental.pallas import tpu as pltpu

    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return {
        "compiler_params": params_cls(dimension_semantics=("arbitrary",))
    }


def _sparse_body(urows_ref, nrows_ref, z_hbm, n_hbm, g_ref, z_out, n_out,
                 zin, nin, zco, nco, in_sem, out_sem, *, br, alpha, beta,
                 l1, l2, narrow_fn):
    """Shared kernel body: double-buffered row-DMA pipeline around one
    VMEM FTRL block. Grid steps run sequentially; scratch slot b%2
    alternates, so block b's fetch was issued at block b-1 and its
    write-back drains under block b+1's compute."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = pl.program_id(0)
    nb = pl.num_programs(0)
    slot = jax.lax.rem(b, 2)
    nxt = jax.lax.rem(b + 1, 2)

    def dma_pair(method, inbound, s, blk):
        # one (z, n) DMA pair per touched table row; starts and waits
        # are gated by the SAME `gi < nrows` predicate, so their counts
        # match exactly and filler rows past nrows move no bytes
        def body(j, _):
            gi = blk * br + j

            @pl.when(gi < nrows_ref[0])
            def _():
                r = urows_ref[gi]
                if inbound:
                    cz = pltpu.make_async_copy(
                        z_hbm.at[r], zin.at[s, j], in_sem.at[s, 0]
                    )
                    cn = pltpu.make_async_copy(
                        n_hbm.at[r], nin.at[s, j], in_sem.at[s, 1]
                    )
                else:
                    cz = pltpu.make_async_copy(
                        zco.at[s, j], z_out.at[r], out_sem.at[s, 0]
                    )
                    cn = pltpu.make_async_copy(
                        nco.at[s, j], n_out.at[r], out_sem.at[s, 1]
                    )
                getattr(cz, method)()
                getattr(cn, method)()

            return 0

        jax.lax.fori_loop(0, br, body, 0)

    # warm-up: the first block fetches its own rows
    @pl.when(b == 0)
    def _():
        dma_pair("start", True, slot, b)

    dma_pair("wait", True, slot, b)

    # prefetch the NEXT block's rows while this block computes — the
    # double buffer that overlaps fetch with compute
    @pl.when(b + 1 < nb)
    def _():
        dma_pair("start", True, nxt, b + 1)

    # the compute below overwrites compute-out slot b%2; block b-2's
    # write-back DMA reads from it, so drain that first
    @pl.when(b >= 2)
    def _():
        dma_pair("wait", False, slot, b - 2)

    # trailing blocks past nrows (the grid is statically sized from the
    # PADDED unique width; row-dedup shrinks the live prefix) have every
    # DMA predicated off — skip their compute too instead of running
    # the full FTRL step (and the bf16 PRNG) on stale scratch
    @pl.when(b * br < nrows_ref[0])
    def _():
        z = zin[slot]
        n = nin[slot].astype(jnp.float32)
        g = g_ref[:]
        z_new, n_new = _ftrl_math(z, n, g, alpha=alpha, beta=beta,
                                  l1=l1, l2=l2)
        # membership per lane: g != 0 (the unquantized-push contract —
        # padding/non-owned lanes carry g = 0, passing through unchanged)
        keep = g != 0
        zco[slot] = jnp.where(keep, z_new, z)
        nco[slot] = narrow_fn(jnp.where(keep, n_new, n))

    dma_pair("start", False, slot, b)

    # drain: the final block waits its own write-back and the previous
    # block's still-in-flight one
    @pl.when(b == nb - 1)
    def _():
        dma_pair("wait", False, slot, b)

        @pl.when(b >= 1)
        def _():
            dma_pair("wait", False, nxt, b - 1)


def _kernel_f32(urows_ref, nrows_ref, z_hbm, n_hbm, g_ref, z_out, n_out,
                zin, nin, zco, nco, in_sem, out_sem, *, br, alpha, beta,
                l1, l2):
    _sparse_body(
        urows_ref, nrows_ref, z_hbm, n_hbm, g_ref, z_out, n_out,
        zin, nin, zco, nco, in_sem, out_sem,
        br=br, alpha=alpha, beta=beta, l1=l1, l2=l2,
        narrow_fn=lambda x: x,
    )


def _kernel_bf16(urows_ref, nrows_ref, seed_ref, z_hbm, n_hbm, g_ref,
                 z_out, n_out, zin, nin, zco, nco, in_sem, out_sem, *,
                 br, alpha, beta, l1, l2):
    """bf16-``sqrt_n`` compiled variant: stochastic f32→bf16 narrow
    with the on-core PRNG, per-block stream (block-correlated rounding
    noise is biased in aggregate — ops/quantize.py note). An
    already-bf16-exact value (untouched lanes) is unchanged by
    construction (low mantissa bits zero)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def narrow(x):
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        rnd = pltpu.bitcast(pltpu.prng_random_bits(x.shape), jnp.uint32)
        bits = pltpu.bitcast(x, jnp.uint32)
        rounded = (bits + (rnd & jnp.uint32(0xFFFF))) & jnp.uint32(
            0xFFFF0000
        )
        return pltpu.bitcast(rounded, jnp.float32).astype(jnp.bfloat16)

    _sparse_body(
        urows_ref, nrows_ref, z_hbm, n_hbm, g_ref, z_out, n_out,
        zin, nin, zco, nco, in_sem, out_sem,
        br=br, alpha=alpha, beta=beta, l1=l1, l2=l2, narrow_fn=narrow,
    )


def _kernel_bf16_dither(urows_ref, nrows_ref, seed_ref, z_hbm, n_hbm,
                        g_ref, didx_ref, z_out, n_out, zin, nin, zco,
                        nco, in_sem, out_sem, *, br, alpha, beta, l1,
                        l2):
    """bf16 interpret-mode variant: ``pltpu.prng_*`` has no CPU
    lowering, so the narrow dithers from :func:`dither_hash_u32`
    indexed by each lane's u-position (``didx``) — the SAME
    (index, seed) stream the jnp reference draws over the gathered
    row vector, which is what makes the parity test BIT-exact. The
    extra [U, 128] index operand only exists on this path; the
    compiled kernel uses the PRNG above and ships no index."""

    def narrow(x):
        rnd = dither_hash_u32(
            didx_ref[:].astype(jnp.uint32),
            seed_ref[0].astype(jnp.uint32),
        )
        bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
        rounded = (bits + (rnd & jnp.uint32(0xFFFF))) & jnp.uint32(
            0xFFFF0000
        )
        return jax.lax.bitcast_convert_type(
            rounded, jnp.float32
        ).astype(jnp.bfloat16)

    _sparse_body(
        urows_ref, nrows_ref, z_hbm, n_hbm, g_ref, z_out, n_out,
        zin, nin, zco, nco, in_sem, out_sem,
        br=br, alpha=alpha, beta=beta, l1=l1, l2=l2, narrow_fn=narrow,
    )


def _sparse_block_rows(u: int, requested: "int | None" = None) -> int:
    """Pallas tile height for the sparse kernel: the requested value
    (arg, else ``PS_FTRL_SPARSE_BLOCK_ROWS``, else 512) through the
    same power-of-two-dividing resolution as the dense kernel. 512
    rows/block keeps the 8 double-buffered [BR, 128] scratch refs
    ~2.5 MB of VMEM while amortizing grid overhead to ~U/512 steps."""
    if requested is None:
        try:
            requested = int(
                os.environ.get("PS_FTRL_SPARSE_BLOCK_ROWS", 512)
            )
        except ValueError:
            requested = 512
    return _choose_block_rows(u, requested)


# no-donate: the public z/n entry point is used by parity tests and the
# A/B bench, which keep their inputs; the fused train step donates at
# ITS boundary and the kernel aliases in-block via input_output_aliases
# (same rule as ops/ftrl.ftrl_update).
@functools.partial(
    jax.jit,  # no-donate: see above — callers keep their z/n inputs
    static_argnames=("alpha", "beta", "l1", "l2", "force_pallas",
                     "interpret", "block_rows"),
)
def ftrl_sparse_update(
    z: jax.Array,
    sqrt_n: jax.Array,
    rel: jax.Array,
    ok: jax.Array,
    g_u: jax.Array,
    *,
    alpha: float,
    beta: float,
    l1: float,
    l2: float = 0.0,
    seed=None,
    force_pallas: bool = False,
    interpret: bool = False,
    block_rows: "int | None" = None,
):
    """Fused sparse-touched FTRL update over a 1-D slot shard.

    ``rel``/``ok`` are ``localize``'s shard-relative ids + ownership
    mask for the batch's globally-deduped ``uslots`` (NON-DECREASING —
    clip of a sorted unique vector — and duplicate-free among ``ok``
    entries: the update is nonlinear in the summed gradient, so host
    prep dedups at slot level; the same apply_state_rows contract).
    ``g_u`` is the per-unique-slot aggregated gradient. Returns
    ``(z', sqrt_n')`` — bit-identical to
    ``updaters.apply_state_rows(FTRLUpdater(decay), ...)``.

    The Pallas path updates the touched rows IN PLACE
    (``input_output_aliases``; callers whose enclosing jit donates the
    state — the fused production step — get it copy-free, same
    defensive-copy caveat as the dense kernel) and moves ONE HBM round
    trip of 128-lane rows: ~1 KB fetched + ~1 KB written per distinct
    touched row (z + f32 √n) plus the in-program [U, 128] gradient
    scatter — against the XLA rows path's four separate gather/scatter
    dispatches. ``seed`` (traced uint32) drives the stochastic bf16
    narrow; ``block_rows`` tiles the row axis (default 512, env
    ``PS_FTRL_SPARSE_BLOCK_ROWS`` — baked at first trace like the
    dense kernel's knob).

    Falls back to :func:`ftrl_sparse_rows_ref` off-TPU and for shapes
    the kernel cannot tile (``use_sparse_kernel``), so any caller can
    use it unconditionally.
    """
    p = z.shape[0]
    u = rel.shape[0]
    bf16_n = sqrt_n.dtype == jnp.bfloat16
    if z.ndim != 1 or not use_sparse_kernel(
        p, u, bf16_n, seed is not None, force_pallas
    ):
        return ftrl_sparse_rows_ref(
            z, sqrt_n, rel, ok, g_u,
            alpha=alpha, beta=beta, l1=l1, l2=l2, seed=seed,
        )
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    table_rows = p // _LANES
    shape2d = (table_rows, _LANES)
    br = _sparse_block_rows(u, block_rows)
    urows, nrows, g_rows, didx = _row_gradient(rel, ok, g_u, u)

    blocked = lambda: pl.BlockSpec(  # noqa: E731 — per-spec instance
        (br, _LANES), lambda i, *_: (i, 0), memory_space=pltpu.VMEM
    )
    any_spec = lambda: pl.BlockSpec(memory_space=pltpu.ANY)  # noqa: E731
    operands = [z.reshape(shape2d), sqrt_n.reshape(shape2d), g_rows]
    in_specs = [any_spec(), any_spec(), blocked()]
    n_prefetch = 2
    prefetch = [urows, nrows]
    if bf16_n:
        n_prefetch = 3
        prefetch.append(jnp.asarray(seed, jnp.int32).reshape(1))
        if interpret:
            kernel = functools.partial(
                _kernel_bf16_dither, br=br, alpha=alpha, beta=beta,
                l1=l1, l2=l2,
            )
            operands.append(didx)
            in_specs.append(blocked())
        else:
            kernel = functools.partial(
                _kernel_bf16, br=br, alpha=alpha, beta=beta, l1=l1,
                l2=l2,
            )
    else:
        kernel = functools.partial(
            _kernel_f32, br=br, alpha=alpha, beta=beta, l1=l1, l2=l2,
        )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(u // br,),
        in_specs=in_specs,
        out_specs=(any_spec(), any_spec()),
        scratch_shapes=[
            pltpu.VMEM((2, br, _LANES), jnp.float32),       # z fetch
            pltpu.VMEM((2, br, _LANES), sqrt_n.dtype),      # n fetch
            pltpu.VMEM((2, br, _LANES), jnp.float32),       # z compute
            pltpu.VMEM((2, br, _LANES), sqrt_n.dtype),      # n compute
            pltpu.SemaphoreType.DMA((2, 2)),                # fetch sems
            pltpu.SemaphoreType.DMA((2, 2)),                # write sems
        ],
    )
    # z/sqrt_n update IN PLACE: without the alias the call materializes
    # fresh z'/n' buffers next to the live table — at 2^30 slots that
    # extra 8 GB is the difference between one chip holding the table
    # or RESOURCE_EXHAUSTED. Alias indices count the scalar-prefetch
    # operands first. Every touched row is read (fetch) strictly before
    # its write-back is issued, and rows are unique across the grid, so
    # the pipeline never observes its own output.
    z_new, n_new = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct(shape2d, z.dtype),
            jax.ShapeDtypeStruct(shape2d, sqrt_n.dtype),
        ),
        input_output_aliases={n_prefetch: 0, n_prefetch + 1: 1},
        interpret=interpret,
        **_grid_params(interpret),
    )(*prefetch, *operands)
    return z_new.reshape(p), n_new.reshape(p)
