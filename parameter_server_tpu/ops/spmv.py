"""Sparse matrix-vector ops for example batches.

The worker-side compute of the reference (Eigen CSR matvec in
``loss.h::compute`` / the hand loops in ``darlin.h::ComputeGradient``)
becomes segment-sum/gather kernels over the padded-COO device encoding
(utils/sparse.py PaddedBatch): all shapes static, padding entries point at a
sentinel column with value 0 so they vanish from every reduction.

A batch arrives *localized*: ``ucols`` indexes into the batch's unique-slot
array, so weight gathers touch each unique feature once (the reference pulls
per unique key for the same reason — kv_vector.h ordered unique keys).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def row_segment_sum(values: jnp.ndarray, rows: jnp.ndarray, num_rows: int) -> jnp.ndarray:
    """sum_{e: rows[e]=i} values[e] → [num_rows]. Xw when values = x_e * w_e."""
    return jax.ops.segment_sum(values, rows, num_segments=num_rows)


def spmv(
    vals: jnp.ndarray,  # [nnz] feature values (0 for padding)
    ucols: jnp.ndarray,  # [nnz] index into w_uniq
    rows: jnp.ndarray,  # [nnz] example ids
    w_uniq: jnp.ndarray,  # [U] weights for the batch's unique features
    num_rows: int,
) -> jnp.ndarray:
    """Xw for a localized padded batch: [num_rows]."""
    return row_segment_sum(vals * w_uniq[ucols], rows, num_rows)


def spmv_t(
    vals: jnp.ndarray,
    ucols: jnp.ndarray,
    rows: jnp.ndarray,
    row_grad: jnp.ndarray,  # [num_rows] d loss / d (Xw)_i
    num_uniq: int,
) -> jnp.ndarray:
    """X^T g: per-unique-feature gradient, [U] (loss.h transTimes)."""
    return jax.ops.segment_sum(vals * row_grad[rows], ucols, num_segments=num_uniq)


def spmv_t_sq(
    vals: jnp.ndarray,
    ucols: jnp.ndarray,
    rows: jnp.ndarray,
    row_h: jnp.ndarray,  # [num_rows] per-row curvature weight
    num_uniq: int,
) -> jnp.ndarray:
    """(X.^2)^T h: diagonal-Hessian accumulation, [U] (loss.h dotTimes path)."""
    return jax.ops.segment_sum(vals * vals * row_h[rows], ucols, num_segments=num_uniq)
