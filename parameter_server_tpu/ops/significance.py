"""KKT-style significance filter for the sparse update path (trace-pure).

The reference parameter server's KKT filter (Li et al., OSDI'14 §5.2)
drops gradient keys whose update provably cannot move the model: for an
L1-regularized objective the FTRL-proximal weight is

    w_k = prox(-z_k * eta, eta) = 0   iff   |z_k| <= lambda1,

so a slot sitting at ``w == 0`` whose post-fold accumulator still lands
inside the dead zone (``|z + g| <= lambda1``) takes an update that is a
provable no-op on the weights — only ``z``/``n`` bookkeeping would move,
and only within the dead zone. Suppressing those slots cuts the shipped
key set on the binding upload path without touching any weight the model
actually uses.

This module is the in-jit half: :func:`kkt_mask` computes the per-slot
keep mask from the GLOBAL unique-slot vectors the sparse mini-step
already assembles (``z_u``/``g_u``/``w_u``/``umask`` — identical on
every shard after their psums, so the mask is too). Decisions are
deterministic and seeded: a fixed escape fraction of suppressed slots
ships anyway (counter-hash of (position, seed), the ops/ftrl.py dither
stream), because a persistent feature whose per-step gradient never
exceeds the dead zone would otherwise NEVER accumulate z and never
learn — the classic KKT-filter starvation mode, disclosed in
doc/PERFORMANCE.md ("Consistency–throughput frontier").

Honest-lossiness contract: with the filter ON, suppressed slots skip
their z/n accumulation (their crossing into the active set is delayed
by ~1/escape steps); with the filter OFF (:data:`SignificanceSpec` is
``None`` at trace time) the traced program is literally unchanged —
bit-identical to the unfiltered path, contract-tested in
tests/test_consistency.py.

jit-purity scope (script/pslint): everything here is trace-pure — no
telemetry, no host sync, no wall clock; counts ride the metrics dict
and are metered host-side in collect (the PR 8 pattern).
"""

# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

#: 2^32 as float — escape probability -> uint32 hash threshold
_U32_SPAN = 4294967296.0


@dataclasses.dataclass(frozen=True)
class SignificanceSpec:
    """Trace-time constants of the KKT significance filter.

    Frozen and hashable: step builders close over it, so two workers
    with the same spec share compiled-step structure and a ``None``
    spec traces exactly the pre-filter program.

    - ``l1``: the penalty's lambda1 (the proximal dead-zone radius).
    - ``margin``: threshold scale on the dead zone. 1.0 is the exact
      optimality condition (suppress only provable weight no-ops);
      < 1 is conservative, > 1 trades accuracy for fewer keys.
    - ``escape``: seeded fraction of otherwise-suppressed slots that
      ship anyway (starvation guard). 0 disables the escape hatch.
    - ``feedback``: emit the per-slot keep mask + slot ids as metrics
      side outputs so the host-side tracker (learner/consistency.py)
      can drop persistently-suppressed keys from future uploads.
      Scan supersteps force this off (per-ministep vectors would be
      summed into garbage by the scan metric fold).
    """

    l1: float
    margin: float = 1.0
    escape: float = 1.0 / 64.0
    feedback: bool = False

    def without_feedback(self) -> "SignificanceSpec":
        return dataclasses.replace(self, feedback=False)


def kkt_mask(z_u, g_u, w_u, umask, seed, *, spec: SignificanceSpec):
    """Per-unique-slot keep mask for the aggregated gradient ``g_u``.

    All inputs are the sparse mini-step's GLOBAL unique vectors
    (identical on every shard): ``z_u`` the assembled FTRL z
    accumulator, ``g_u`` the data-psum'd gradient, ``w_u`` the pulled
    weights, ``umask`` the real-slot (non-padding) mask. Returns
    ``(keep, suppressed)``: a bool keep vector and the scalar count of
    suppressed real slots. Padding slots always read keep=True (their
    gradient is already zero and they must stay out of the count).

    The decision is evaluated on the τ-stale PULLED state — the same
    snapshot the gradient itself was computed on — so it composes with
    bounded-delay staleness exactly like the gradient does.
    """
    if spec.escape >= 1.0:
        # every suppressed slot would escape: the filter is a
        # structural no-op (the bit-identity configuration the
        # contract tests pin) — skip the mask entirely so the traced
        # update path is untouched
        return (
            jnp.ones_like(umask, dtype=bool),
            jnp.zeros((), jnp.float32),
        )
    at_zero = (w_u == 0.0) & (umask > 0)
    # the FTRL z fold at w == 0 is z' = z + g (sigma*w vanishes): the
    # slot stays a provable weight no-op iff z' is inside the scaled
    # dead zone
    insig = jnp.abs(z_u + g_u) <= np.float32(spec.l1 * spec.margin)
    suppress = at_zero & insig
    if spec.escape > 0.0:
        from .ftrl import dither_hash_u32

        # seeded starvation escape: a fixed fraction of suppressed
        # slots ships each step so persistent sub-threshold gradients
        # still accumulate z at rate ~escape*g. Position-keyed on the
        # dither stream, offset from the rounding dither's seed use so
        # the two decision streams never correlate.
        pos = jnp.arange(z_u.shape[0], dtype=jnp.uint32)
        h = dither_hash_u32(pos, jnp.asarray(seed, jnp.uint32) ^ np.uint32(0x5EED5EED))
        esc = h < np.uint32(int(spec.escape * _U32_SPAN))
        suppress = suppress & ~esc
    keep = ~suppress
    return keep, jnp.sum(suppress.astype(jnp.float32))
