"""Fused FTRL-proximal update — Pallas TPU kernel.

The server-side hot op (ref FTRLEntry::Set, async_sgd.h:131-151) as one
VMEM-resident pass: reads (z, √n, g, touched), emits (z', √n') with the
weight derivation inlined, so the whole per-shard state update is a single
HBM round trip. Grid tiles the slot dimension in (8,128)-aligned blocks.

``sqrt_n`` may be stored bf16 (``SGDConfig.ftrl_state_dtype`` — 12
B/slot table state): math widens to f32 and the write-back narrows with
STOCHASTIC rounding (on-core PRNG in the kernel; hash dither in the jnp
path) — deterministic truncation would saturate the accumulator by
absorption once n >> per-update increment, freezing the per-coordinate
learning-rate decay for hot features.

``ftrl_update(z, n, g, touched, ...)`` auto-selects: Pallas on TPU backends,
pure-jnp elsewhere (bit-identical math in f32; tests compare both).
"""
# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

_LANES = 128
_SUBLANES = 8
_TILE = _LANES * _SUBLANES


def _use_pallas() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def xla_min_slots() -> int:
    """Dense-update formulation flip point, in slots — DISABLED by
    default (2^62 ≈ never). The only committed capture (BENCH_ONCHIP
    2026-08-02 16:12: ftrl_dense_xla_2e28 17.8 ms vs
    ftrl_dense_pallas_2e28 29.3 ms) nominally favors XLA, but that
    single-pass form is confounded twice over: it charges the Pallas
    arm defensive whole-table copies for its input_output_aliases (the
    ftrl_update docstring's own warning) and buries both arms under a
    ~14.5 ms per-dispatch tunnel floor — so it cannot decide the flip,
    and the default stays disabled on that methodology argument.

    The corrected measurement is now COMMITTED as a registered bench:
    ``benchmarks/components.ftrl_chain`` (``make ftrl-bench``; also in
    every on-chip ``make bench-all``) chains 8 donated updates per
    dispatch, which amortizes the dispatch floor 8x and gives the
    kernel its production aliasing. Derivation once a device capture
    lands in BENCH_ONCHIP.md: flip = the smallest sweep size whose
    ``ftrl_dense_xla_2e{K}_chain_per_update_ms`` beats
    ``ftrl_dense_pallas_2e{K}_chain_per_update_ms`` (sizes above the
    crossover set this default; no crossover → stays 2^62). The
    un-retained same-day chain run had Pallas ahead at every size,
    predicting "no flip", but only a committed capture re-judges the
    default (doc/PERFORMANCE.md, "FTRL roofline"). Env
    ``PS_FTRL_XLA_MIN_SLOTS`` remains as the sweep override; the value
    is baked at trace time per shape (jit static caching)."""
    try:
        return int(os.environ.get("PS_FTRL_XLA_MIN_SLOTS", 1 << 62))
    except ValueError:
        return 1 << 62


def use_ref_path(p: int, bf16_n: bool, has_seed: bool,
                 force_pallas: bool) -> bool:
    """Pure path-selection predicate for ``ftrl_update`` (testable off
    device): the jnp/XLA reference path runs off-TPU, for non-tileable
    shards, for an unseeded bf16 narrow, and — by measurement — for
    big tables (``xla_min_slots``). ``force_pallas`` pins the kernel
    for A/B sweeps and kernel tests, but never onto a shard the kernel
    cannot tile or narrow correctly."""
    if not force_pallas and not _use_pallas():
        return True
    if p % _TILE != 0 or (bf16_n and not has_seed):
        return True
    if force_pallas:
        return False
    return p >= xla_min_slots()


def dither_hash_u32(i: jnp.ndarray, seed) -> jnp.ndarray:
    """THE dither stream: a counter-based integer hash of
    (index, seed) — cheap, stateless, vectorized; rounding dither
    needs uniformity, not cryptographic quality. ``i`` is a uint32
    index array (position counters, or the sparse kernel's u-position
    map); ``seed`` a uint32 scalar. Single copy shared by
    :func:`stochastic_round_bf16`, :func:`_hash_dither_bits`, and the
    sparse kernel's dither substitute (ops/ftrl_sparse.py), so the
    interpret-mode parity contract — same (index, seed) in, same
    dither out — cannot drift between the jnp path and a kernel."""
    h = (i * np.uint32(2654435761)) ^ (
        jnp.asarray(seed, jnp.uint32) * np.uint32(0x9E3779B9)
    )
    h = (h ^ (h >> 15)) * np.uint32(0x85EBCA6B)
    h = (h ^ (h >> 13)) * np.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


def stochastic_round_bf16(x: jnp.ndarray, seed) -> jnp.ndarray:
    """Unbiased f32 -> bf16 narrowing (jnp path): add hash-derived
    uniform dither in [0, 2^16) to the f32 bits, then truncate the low
    mantissa bits. E[rounded] = x, so a bf16 accumulator performs an
    unbiased walk instead of stalling by absorption. The dither indexes
    :func:`dither_hash_u32` by flat position. Values whose f32 form is
    already exactly bf16 (e.g. untouched slots round-tripped through
    storage) are returned unchanged for every dither draw."""
    bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    i = jax.lax.iota(jnp.uint32, max(1, x.size)).reshape(x.shape)
    rnd = dither_hash_u32(i, jnp.uint32(seed)) & np.uint32(0xFFFF)
    out = (bits + rnd) & np.uint32(0xFFFF0000)
    return jax.lax.bitcast_convert_type(out, jnp.float32).astype(
        jnp.bfloat16
    )


def _ftrl_math(z, n, g, *, alpha, beta, l1, l2):
    """The FTRL-proximal step on f32 operands — THE single copy of the
    math, shared by the jnp reference and both kernel variants (a fix
    applied to one copy cannot miss the others)."""
    eta = alpha / (n + beta)
    zt = -z * eta
    w = jnp.sign(zt) * jnp.maximum(jnp.abs(zt) - l1 * eta, 0.0) / (1.0 + l2 * eta)
    n_new = jnp.sqrt(n * n + g * g)
    sigma = (n_new - n) / alpha
    z_new = z + g - sigma * w
    return z_new, n_new


def ftrl_update_ref(z, sqrt_n, grad, touched, *, alpha, beta, l1, l2,
                    seed=None):
    """Pure-jnp reference (identical to updaters.FTRLUpdater.apply math).
    bf16 sqrt_n widens for math; the narrow is stochastically rounded
    when ``seed`` is given, else deterministically. ``touched=None``
    derives membership as ``grad != 0`` (the unquantized-push
    contract, async_sgd.make_push_touched)."""
    if touched is None:
        touched = grad != 0
    store_dtype = sqrt_n.dtype
    sqrt_n = sqrt_n.astype(jnp.float32)
    z_new, sqrt_n_new = _ftrl_math(
        z, sqrt_n, grad, alpha=alpha, beta=beta, l1=l1, l2=l2
    )
    n_out = jnp.where(touched, sqrt_n_new, sqrt_n)
    if store_dtype == jnp.bfloat16 and seed is not None:
        n_out = stochastic_round_bf16(n_out, seed)
    return jnp.where(touched, z_new, z), n_out.astype(store_dtype)


def _kernel(z_ref, n_ref, g_ref, t_ref, z_out, n_out, *, alpha, beta, l1, l2):
    # t_ref=None: membership derived in-block as g != 0 (the
    # unquantized-push contract) — at 2^30 slots the f32 mask operand
    # alone is 4 GB of HBM, so deriving it is what lets the table fit
    z = z_ref[:]
    n = n_ref[:]
    g = g_ref[:]
    z_new, n_new = _ftrl_math(z, n, g, alpha=alpha, beta=beta, l1=l1, l2=l2)
    keep = (t_ref[:] > 0) if t_ref is not None else (g != 0)
    z_out[:] = jnp.where(keep, z_new, z)
    n_out[:] = jnp.where(keep, n_new, n)


def _kernel_nomask(z_ref, n_ref, g_ref, z_out, n_out, *, alpha, beta, l1,
                   l2):
    _kernel(z_ref, n_ref, g_ref, None, z_out, n_out,
            alpha=alpha, beta=beta, l1=l1, l2=l2)


def _hash_dither_bits(seed_scalar, shape):
    """Interpret-mode dither source: the same counter-hash used by
    :func:`stochastic_round_bf16`, as raw uint32 bits. Interpret mode
    cannot execute ``pltpu.prng_*`` (no CPU lowering), so the kernel
    body is tested with this substitute while the PRNG path itself is
    pinned by tests/test_mosaic_lowering.py."""
    n = 1
    for d in shape:
        n *= d
    i = jax.lax.iota(jnp.uint32, n).reshape(shape)
    return dither_hash_u32(i, seed_scalar.astype(jnp.uint32))


def _kernel_bf16(z_ref, n_ref, g_ref, t_ref, seed_ref, z_out, n_out, *,
                 alpha, beta, l1, l2, dither_fn=None):
    """bf16-``sqrt_n`` variant: widen in VMEM, stochastically round the
    narrow with the on-core PRNG (per-block stream — block-correlated
    rounding noise is biased in aggregate, ops/quantize.py note).
    ``dither_fn``: interpret-mode substitute for the PRNG (see
    :func:`_hash_dither_bits`). ``t_ref=None``: membership derived
    in-block as ``g != 0`` (see :func:`_kernel`)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    z = z_ref[:]
    n = n_ref[:].astype(jnp.float32)
    g = g_ref[:]
    z_new, n_new = _ftrl_math(z, n, g, alpha=alpha, beta=beta, l1=l1, l2=l2)
    keep = (t_ref[:] > 0) if t_ref is not None else (g != 0)
    z_out[:] = jnp.where(keep, z_new, z)
    n_keep = jnp.where(keep, n_new, n)
    # stochastic f32->bf16: dither the low 16 bits, truncate. An
    # already-bf16-exact value (untouched slots) is unchanged by
    # construction (its low mantissa bits are zero).
    if dither_fn is None:
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        rnd = pltpu.bitcast(
            pltpu.prng_random_bits(n_keep.shape), jnp.uint32
        )
        bits = pltpu.bitcast(n_keep, jnp.uint32)
        rounded = (bits + (rnd & jnp.uint32(0xFFFF))) & jnp.uint32(0xFFFF0000)
        n_out[:] = pltpu.bitcast(rounded, jnp.float32).astype(jnp.bfloat16)
    else:
        rnd = dither_fn(seed_ref[0] + pl.program_id(0), n_keep.shape)
        bits = jax.lax.bitcast_convert_type(n_keep, jnp.uint32)
        rounded = (bits + (rnd & jnp.uint32(0xFFFF))) & jnp.uint32(0xFFFF0000)
        n_out[:] = jax.lax.bitcast_convert_type(
            rounded, jnp.float32
        ).astype(jnp.bfloat16)


def _kernel_bf16_nomask(z_ref, n_ref, g_ref, seed_ref, z_out, n_out, *,
                        alpha, beta, l1, l2, dither_fn=None):
    _kernel_bf16(z_ref, n_ref, g_ref, None, seed_ref, z_out, n_out,
                 alpha=alpha, beta=beta, l1=l1, l2=l2,
                 dither_fn=dither_fn)


def _choose_block_rows(rows: int, requested: "int | None" = None) -> int:
    """Resolve the Pallas tile height: the requested value (arg, else
    PS_FTRL_BLOCK_ROWS, else 2048) rounded DOWN to a power of two ≥ 8,
    then halved until it divides ``rows``. Pure so the selection is
    directly testable — a naive halving loop preserved odd factors
    (1536 → ... → 3 → 1) and could emit a sub-(8,128)-tile block."""
    # loud, not partial: a non-multiple-of-8 rows cannot be tiled by
    # any power-of-two ≥ 8 and grid=rows//br would silently skip the
    # tail. ValueError, not assert: input validation must survive
    # python -O (ftrl_update's p % _TILE gate guarantees it; direct
    # callers get the error)
    if rows % 8:
        raise ValueError(f"rows={rows} not a multiple of 8")
    if requested is None:
        try:
            requested = int(os.environ.get("PS_FTRL_BLOCK_ROWS", 2048))
        except ValueError:
            requested = 2048
    br = 1 << max(3, int(requested).bit_length() - 1)
    while rows % br and br > 8:
        br //= 2
    return br


# The public z/n entry point is used by parity tests and snapshot
# paths that keep their inputs; the fused train steps donate at THEIR
# boundary (and the Pallas path aliases in-block via
# input_output_aliases), so jit-level donation here would only poison
# callers' buffers without removing a copy.
@functools.partial(
    jax.jit,  # no-donate: see above — callers keep their z/n inputs
    static_argnames=("alpha", "beta", "l1", "l2", "force_pallas",
                     "interpret", "block_rows"),
)
def ftrl_update(
    z: jax.Array,
    sqrt_n: jax.Array,
    grad: jax.Array,
    touched: jax.Array,
    *,
    alpha: float,
    beta: float,
    l1: float,
    l2: float = 0.0,
    seed=None,
    force_pallas: bool = False,
    interpret: bool = False,
    block_rows: "int | None" = None,
):
    """Fused update over a 1-D slot shard. touched: bool/float mask,
    or ``None`` to derive membership in-kernel as ``grad != 0`` (valid
    exactly when the push is unquantized — async_sgd.make_push_touched
    — and worth it: no table-sized mask operand, which at 2^30 slots
    saves 4 GB of HBM).
    ``seed`` (traced uint32 scalar) drives the stochastic narrow when
    ``sqrt_n`` is stored bf16; without it the bf16 narrow truncates
    (callers that care about long-horizon LR decay must pass one).

    The Pallas kernel updates z/sqrt_n IN PLACE (input_output_aliases
    — what lets one chip hold a 2^30 table). Callers whose enclosing
    jit DONATES the state (the fused production step, max_delay=0)
    get the update copy-free; at a non-donating call site XLA inserts
    defensive whole-table copies of z/sqrt_n to preserve the caller's
    buffers — correct, but one extra table read+write. Benchmarks
    must therefore time the donated form (benchmarks/components.py
    ftrl phase).

    ``block_rows`` tiles the slot dimension (default 2048 = 1 MB/ref;
    env ``PS_FTRL_BLOCK_ROWS`` overrides so a cross-process on-chip
    block-size sweep needs no code edit); non-dividing values round
    down to the largest dividing power-of-two slice. The env value is
    baked at FIRST trace of the ``block_rows=None`` variant (jit
    static caching) — an in-process sweep must pass ``block_rows``
    explicitly, which retraces per value.

    Falls back to the jnp reference path off-TPU and for shards that are not
    tile-aligned, so any caller can use it unconditionally.
    """
    p = z.shape[0]
    bf16_n = sqrt_n.dtype == jnp.bfloat16
    if z.ndim != 1 or use_ref_path(
        p, bf16_n, seed is not None, force_pallas
    ):
        return ftrl_update_ref(
            z, sqrt_n, grad,
            None if touched is None else touched.astype(jnp.float32) > 0,
            alpha=alpha, beta=beta, l1=l1, l2=l2, seed=seed,
        )
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    rows = p // _LANES
    shape2d = (rows, _LANES)
    # big blocks: 6 refs/block (4 in + 2 out) must fit VMEM, but a tiny
    # (8,128) block makes the grid enormous on multi-M-slot tables (2^26
    # slots -> 65536 steps) and grid overhead swamps the math. 2048x128
    # = 1MB/ref keeps the grid <= a few hundred steps at every real size.
    block_rows = _choose_block_rows(rows, block_rows)
    grid = (rows // block_rows,)
    spec = pl.BlockSpec(
        (block_rows, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    out_shape = (
        jax.ShapeDtypeStruct(shape2d, z.dtype),
        jax.ShapeDtypeStruct(shape2d, sqrt_n.dtype),
    )
    # z/sqrt_n update IN PLACE (input_output_aliases): without the
    # alias the call materializes fresh z'/n' buffers next to the live
    # table — at 2^30 slots that extra 8 GB is the difference between
    # one chip holding the table or RESOURCE_EXHAUSTED (the donated
    # step's own aliasing only covers program input->output, not this
    # call's operands). Block i is read before it is written, so the
    # grid pipeline never observes its own output.
    operands = [z.reshape(shape2d), sqrt_n.reshape(shape2d),
                grad.reshape(shape2d)]
    in_specs = [spec, spec, spec]
    if touched is not None:
        operands.append(touched.astype(jnp.float32).reshape(shape2d))
        in_specs.append(spec)
    if bf16_n:
        kernel = functools.partial(
            _kernel_bf16 if touched is not None else _kernel_bf16_nomask,
            alpha=alpha, beta=beta, l1=l1, l2=l2,
            dither_fn=_hash_dither_bits if interpret else None,
        )
        operands.append(jnp.asarray(seed, jnp.int32).reshape(1))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    else:
        kernel = functools.partial(
            _kernel if touched is not None else _kernel_nomask,
            alpha=alpha, beta=beta, l1=l1, l2=l2,
        )
    z_new, n_new = pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=out_shape,
        in_specs=in_specs,
        out_specs=(spec, spec),
        input_output_aliases={0: 0, 1: 1},
        interpret=interpret,
    )(*operands)
    return z_new.reshape(p), n_new.reshape(p)
