"""Fused FTRL-proximal update — Pallas TPU kernel.

The server-side hot op (ref FTRLEntry::Set, async_sgd.h:131-151) as one
VMEM-resident pass: reads (z, √n, g, touched), emits (z', √n') with the
weight derivation inlined, so the whole per-shard state update is a single
HBM round trip. Grid tiles the slot dimension in (8,128)-aligned blocks.

``ftrl_update(z, n, g, touched, ...)`` auto-selects: Pallas on TPU backends,
pure-jnp elsewhere (bit-identical math; tests compare both).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_LANES = 128
_SUBLANES = 8
_TILE = _LANES * _SUBLANES


def _use_pallas() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def ftrl_update_ref(z, sqrt_n, grad, touched, *, alpha, beta, l1, l2):
    """Pure-jnp reference (identical to updaters.FTRLUpdater.apply math)."""
    eta = alpha / (sqrt_n + beta)
    zt = -z * eta
    w = jnp.sign(zt) * jnp.maximum(jnp.abs(zt) - l1 * eta, 0.0) / (1.0 + l2 * eta)
    sqrt_n_new = jnp.sqrt(sqrt_n * sqrt_n + grad * grad)
    sigma = (sqrt_n_new - sqrt_n) / alpha
    z_new = z + grad - sigma * w
    return (
        jnp.where(touched, z_new, z),
        jnp.where(touched, sqrt_n_new, sqrt_n),
    )


def _kernel(z_ref, n_ref, g_ref, t_ref, z_out, n_out, *, alpha, beta, l1, l2):
    z = z_ref[:]
    n = n_ref[:]
    g = g_ref[:]
    t = t_ref[:]
    eta = alpha / (n + beta)
    zt = -z * eta
    w = jnp.sign(zt) * jnp.maximum(jnp.abs(zt) - l1 * eta, 0.0) / (1.0 + l2 * eta)
    n_new = jnp.sqrt(n * n + g * g)
    sigma = (n_new - n) / alpha
    z_new = z + g - sigma * w
    keep = t > 0
    z_out[:] = jnp.where(keep, z_new, z)
    n_out[:] = jnp.where(keep, n_new, n)


@functools.partial(
    jax.jit, static_argnames=("alpha", "beta", "l1", "l2", "force_pallas")
)
def ftrl_update(
    z: jax.Array,
    sqrt_n: jax.Array,
    grad: jax.Array,
    touched: jax.Array,
    *,
    alpha: float,
    beta: float,
    l1: float,
    l2: float = 0.0,
    force_pallas: bool = False,
):
    """Fused update over a 1-D slot shard. touched: bool/float mask.

    Falls back to the jnp reference path off-TPU and for shards that are not
    tile-aligned, so any caller can use it unconditionally.
    """
    p = z.shape[0]
    if not (force_pallas or _use_pallas()) or z.ndim != 1 or p % _TILE != 0:
        return ftrl_update_ref(
            z, sqrt_n, grad, touched.astype(jnp.float32) > 0,
            alpha=alpha, beta=beta, l1=l1, l2=l2,
        )
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    rows = p // _LANES
    shape2d = (rows, _LANES)
    # big blocks: 6 refs/block (4 in + 2 out) must fit VMEM, but a tiny
    # (8,128) block makes the grid enormous on multi-M-slot tables (2^26
    # slots -> 65536 steps) and grid overhead swamps the math. 2048x128
    # = 1MB/ref keeps the grid <= a few hundred steps at every real size.
    block_rows = 2048
    while rows % block_rows:
        block_rows //= 2
    grid = (rows // block_rows,)
    t2d = touched.astype(jnp.float32).reshape(shape2d)
    spec = pl.BlockSpec(
        (block_rows, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    kernel = functools.partial(_kernel, alpha=alpha, beta=beta, l1=l1, l2=l2)
    z_new, n_new = pl.pallas_call(
        kernel,
        grid=grid,
        out_shape=(
            jax.ShapeDtypeStruct(shape2d, z.dtype),
            jax.ShapeDtypeStruct(shape2d, sqrt_n.dtype),
        ),
        in_specs=[spec, spec, spec, spec],
        out_specs=(spec, spec),
    )(z.reshape(shape2d), sqrt_n.reshape(shape2d), grad.reshape(shape2d), t2d)
    return z_new.reshape(p), n_new.reshape(p)
