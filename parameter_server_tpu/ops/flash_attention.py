"""Flash attention — Pallas TPU kernels (forward + backward).

The long-context compute core: blockwise attention with an online-softmax
accumulator held in VMEM, so the [Sq, Sk] score matrix never touches HBM
(memory O(block) instead of O(S^2)) and every matmul is an MXU-shaped
``dot_general``. This is the per-device building block that
``models.attention`` composes with sequence parallelism: ring attention
calls it once per ICI hop with the visiting K/V chunk's global offset, and
merges chunks with the returned logsumexp.

Layout note (why everything is "transposed"): scores are computed as
``s_t[k, q]`` — K on sublanes, Q on lanes — so the per-row softmax
statistics (max, sum, lse, delta) are naturally ``[1, block_q]`` lane
vectors, which is the layout Mosaic wants for broadcasting against both
the score block and the ``[D, block_q]`` output accumulator. No in-kernel
transposes; the output is materialized as ``[BH, D, Sq]`` and transposed
once by XLA outside the kernel.

Reference parity: the reference has no attention op (linear methods +
CXXNET convnets); this kernel exists for the framework's first-class
long-context requirement. Math follows Dao et al.'s FlashAttention-2
recurrence; structure follows the canonical TPU grid pattern
(grid = (batch*heads, q blocks, k blocks), k innermost, accumulators in
VMEM scratch persisted across the k dimension).

``flash_attention(q, k, v, ...)`` auto-selects: Pallas on TPU backends,
an identical-math XLA path elsewhere (tests force the kernels through
interpret mode and compare both, values and gradients).

On-chip parity tolerance (DECIDED, not deferred — the 2026-07-31
BENCH_ONCHIP flash run flagged 6 fwd cases at 1.4e-4..2.6e-4): that
error is bf16-TRUNCATION scale, not a masking or recurrence bug. The
evidence: under default precision the v5e MXU truncates matmul inputs
to bf16 (eps ~8e-3 relative; at these operand magnitudes ~1e-4..1e-3
absolute), the two paths accumulate P·V in different orders (flash:
chunked online-softmax rescaling; XLA: one matmul over the full row),
and every signal that would expose a LOGIC bug is clean — the lse
stats agree to ~8e-6, all nine gradients to ≤5e-5, and interpret mode
(exact f32 both paths) matches to ~1e-7 including the sub-sublane
shapes. script/onchip.py's flash task therefore pins fwd outputs at
5e-4 absolute on chip (2e-5 in interpret mode) with lse at 2e-4 —
tight enough to catch any real recurrence break, loose enough not to
flag the MXU's number format. Serving decode rides this kernel; the
guarantee that matters there (speculative greedy == plain greedy,
token-for-token) is integer-exact and pinned separately in
tests/test_speculative.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_LANE = 128
_SUBLANE = 8  # f32 sublane tile: stat vectors are stored [.., 8, S] because
# Mosaic requires block shapes tileable to (8, 128) — row 0 carries the data
_NEG = -1e30  # finite mask value: keeps exp/max arithmetic NaN-free


def _use_pallas() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    n = x.shape[axis]
    pad = (-n) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# ---------------------------------------------------------------------------
# reference path (XLA): identical math, used off-TPU and in tests
# ---------------------------------------------------------------------------


def flash_attention_ref(q, k, v, q_offset, k_offset, *, causal, window=None):
    """[BH, Sq, D] x [BH, Sk, D] -> (out [BH, Sq, D], lse [BH, Sq]).

    lse is the base-e logsumexp of the masked score rows; fully-masked
    rows return out=0 and lse=_NEG (the merge weight then underflows to
    zero exactly like the kernel path). ``window`` (with causal) keeps
    only keys with 0 <= q_pos - k_pos < window (sliding-window/local
    attention)."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    if causal:
        qp = q_offset + jnp.arange(q.shape[1])
        kp = k_offset + jnp.arange(k.shape[1])
        keep = qp[:, None] >= kp[None, :]
        if window is not None:
            keep &= (qp[:, None] - kp[None, :]) < window
        s = jnp.where(keep[None], s, _NEG)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    p = jnp.where(s <= _NEG / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32))
    out = out / jnp.maximum(l, 1e-30)[..., None]
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), _NEG)
    return out.astype(q.dtype), lse


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(
    qo_ref, ko_ref, q_ref, k_ref, v_ref, out_ref, lse_ref,
    acc_ref, m_ref, l_ref, *, causal, scale, nk, k_len, block_q, block_k,
    window,
):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)

    iq = pl.program_id(1)
    q_off = qo_ref[0, 0]
    k_off = ko_ref[0, 0]
    q_pos = q_off + iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_q), 1
    )
    k_base = ik * block_k
    live = _block_live(q_off, iq, block_q, k_off, k_base, block_k, causal, window)

    @pl.when(live)
    def _update():
        # dot OPERANDS stay in the input dtype (bf16 runs the MXU in one
        # pass; an f32 upcast would force multi-pass emulation) while
        # every dot ACCUMULATES in f32 via preferred_element_type and
        # all softmax/statistics math is f32 — the FlashAttention-on-TPU
        # standard precision recipe. For f32 inputs nothing changes.
        q = q_ref[0]  # [bq, D]
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]
        s_t = jax.lax.dot_general(  # [bk, bq]: K sublanes, Q lanes
            k, q, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        k_pos = k_base + jax.lax.broadcasted_iota(jnp.int32, (block_k, 1), 0)
        valid = k_pos < k_len  # tail padding of the K axis
        if causal:
            valid = valid & (k_off + k_pos <= q_pos)
            if window is not None:
                valid = valid & (q_pos - (k_off + k_pos) < window)
        s_t = jnp.where(valid, s_t, _NEG)
        m_prev = m_ref[...]  # [1, bq]
        m_cur = jnp.max(s_t, axis=0, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p_t = jnp.exp(s_t - m_new)
        p_t = jnp.where(valid, p_t, 0.0)
        corr = jnp.exp(m_prev - m_new)  # [1, bq]
        l_ref[...] = l_ref[...] * corr + jnp.sum(p_t, axis=0, keepdims=True)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            v, p_t.astype(v.dtype), (((0,), (0,)), ((), ())),  # [D, bq]
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(ik == nk - 1)
    def _write():
        l = l_ref[...]
        out_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(
            out_ref.dtype
        )
        lse = jnp.where(
            l > 0, m_ref[...] + jnp.log(jnp.maximum(l, 1e-30)), _NEG
        )  # [1, bq] -> broadcast over the sublane-tile dim
        lse_ref[...] = jnp.broadcast_to(lse[None], lse_ref.shape)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _block_live(q_off, iq, block_q, k_off, k_base, block_k, causal, window):
    """Whole-block skip predicate, shared by the forward and BOTH backward
    kernels so the bound can never diverge between them: a block is dead
    when (causal) even the LAST q row precedes the FIRST k row, or
    (window) even the FIRST q row is past the LAST k row's window."""
    if not causal:
        return True
    live = q_off + iq * block_q + block_q - 1 >= k_off + k_base
    if window is not None:
        live &= q_off + iq * block_q - (k_off + k_base + block_k - 1) < window
    return live


def _recompute_pt(q, k, lse_blk, *, causal, scale, q_pos, k_pos, k_len,
                  window=None):
    """Shared bwd score recomputation: p_t [bk, bq] from saved lse.
    ``q_pos`` arrives with k_offset already subtracted, so the window
    test is directly q_pos - k_pos."""
    s_t = jax.lax.dot_general(
        k, q, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    valid = k_pos < k_len
    if causal:
        valid = valid & (k_pos + 0 <= q_pos)
        if window is not None:
            valid = valid & (q_pos - k_pos < window)
    # exp(s - lse): rows with lse=_NEG (fully masked) still produce 0
    # because s itself is masked to _NEG there as well
    s_t = jnp.where(valid, s_t, _NEG)
    p_t = jnp.exp(s_t - lse_blk)
    return jnp.where(valid, p_t, 0.0)


def _bwd_dq_kernel(
    qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, c_ref, dq_ref,
    acc_ref, *, causal, scale, nk, k_len, block_q, block_k, window,
):
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    iq = pl.program_id(1)
    q_off = qo_ref[0, 0]
    k_off = ko_ref[0, 0]
    live = _block_live(
        q_off, iq, block_q, k_off, ik * block_k, block_k, causal, window
    )

    @pl.when(live)
    def _update():
        # native-dtype dot operands, f32 accumulation + f32 softmax math
        # (see _fwd_kernel's precision note); ds is cast back to the
        # input dtype for the dk/dq matmuls, as in the reference TPU
        # flash kernels
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]  # [bq, D]
        q_pos = q_off + iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_q), 1
        ) - k_off
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0
        )
        p_t = _recompute_pt(
            q, k, lse_ref[0][:1], causal=causal, scale=scale,
            q_pos=q_pos, k_pos=k_pos, k_len=k_len, window=window,
        )
        dp_t = jax.lax.dot_general(  # [bk, bq] = v . do^T
            v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds_t = p_t * (dp_t - c_ref[0][:1]) * scale
        acc_ref[...] += jax.lax.dot_general(  # [D, bq] += k^T . ds_t
            k, ds_t.astype(k.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == nk - 1)
    def _write():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, c_ref,
    dk_ref, dv_ref, dk_acc, dv_acc, *, causal, scale, nq, k_len,
    block_q, block_k, window,
):
    iq = pl.program_id(2)  # q innermost here

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    ik = pl.program_id(1)
    q_off = qo_ref[0, 0]
    k_off = ko_ref[0, 0]
    live = _block_live(
        q_off, iq, block_q, k_off, ik * block_k, block_k, causal, window
    )

    @pl.when(live)
    def _update():
        # native-dtype dot operands, f32 accumulation + f32 softmax math
        # (see _fwd_kernel's precision note)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        q_pos = q_off + iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_q), 1
        ) - k_off
        k_pos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0
        )
        p_t = _recompute_pt(
            q, k, lse_ref[0][:1], causal=causal, scale=scale,
            q_pos=q_pos, k_pos=k_pos, k_len=k_len, window=window,
        )
        dv_acc[...] += jax.lax.dot_general(  # [bk, D] += p_t . do
            p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds_t = p_t * (dp_t - c_ref[0][:1]) * scale
        dk_acc[...] += jax.lax.dot_general(  # [bk, D] += ds_t . q
            ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(iq == nq - 1)
    def _write():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call drivers
# ---------------------------------------------------------------------------

try:  # import at module scope so kernels can reference pl.program_id
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover - pallas always present in this image
    pl = None
    pltpu = None


def _blocks(sq: int, sk: int, block_q: int, block_k: int):
    """Block sizes clamped to the (sublane-rounded) sequence lengths.

    Small-shape hardening (the BENCH_ONCHIP block-spec crash class): a
    block's trailing dims must be (8, 128)-tileable or exactly equal to
    the array dims, and tiny decode-path shapes (a gamma+1 speculative
    verify chunk, a 1-row serving prompt) land BELOW the sublane tile.
    Rounding the clamp up to a multiple of ``_SUBLANE`` — with the
    sequence axes padded to match in the drivers — keeps every block
    spec divisible-by-(8,128) unconditionally instead of leaning on the
    equal-to-array escape hatch, which is exactly the clause that has
    shifted between Mosaic versions. Padding rows are masked the same
    way the lane padding already is (k via ``k_len``; q rows are
    sliced off, and the bwd drivers force their lse so p underflows
    to 0)."""
    bq = min(block_q, -(-max(sq, 1) // _SUBLANE) * _SUBLANE)
    bk = min(block_k, -(-max(sk, 1) // _SUBLANE) * _SUBLANE)
    return bq, bk


def _grid_params(interpret: bool):
    """Grid semantics for Mosaic: batch*heads and the outer block axis
    are parallel (independent accumulator streams — Mosaic may pipeline
    and reorder them); the innermost axis is 'arbitrary' (sequential:
    it carries the online-softmax / accumulator recurrence across
    iterations). Interpret mode takes no compiler params.

    ``CompilerParams`` is the current pallas-tpu name; jax 0.4.x (this
    repo's CPU CI container) still calls it ``TPUCompilerParams`` — the
    getattr chain keeps real-Mosaic lowering testable on both."""
    if interpret:
        return {}
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return {
        "compiler_params": params_cls(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    }


def _fwd_pallas(q, k, v, q_offset, k_offset, *, causal, block_q, block_k,
                interpret, window):
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    bq, bk = _blocks(sq, sk, block_q, block_k)
    qp = _pad_to(_pad_to(q, 1, bq), 2, _LANE)
    kp = _pad_to(_pad_to(k, 1, bk), 2, _LANE)
    vp = _pad_to(_pad_to(v, 1, bk), 2, _LANE)
    dp_ = qp.shape[2]
    nq, nk = qp.shape[1] // bq, kp.shape[1] // bk
    qo = q_offset.astype(jnp.int32).reshape(1, 1)
    ko = k_offset.astype(jnp.int32).reshape(1, 1)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    out_t, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, scale=scale, nk=nk, k_len=sk,
            block_q=bq, block_k=bk, window=window,
        ),
        grid=(bh, nq, nk),
        in_specs=[
            smem,
            smem,
            pl.BlockSpec((1, bq, dp_), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, dp_), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, dp_), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, dp_, bq), lambda b, i, j: (b, 0, i)),
            pl.BlockSpec((1, _SUBLANE, bq), lambda b, i, j: (b, 0, i)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, dp_, qp.shape[1]), q.dtype),
            jax.ShapeDtypeStruct((bh, _SUBLANE, qp.shape[1]), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((dp_, bq), jnp.float32),
            pltpu.VMEM((1, bq), jnp.float32),
            pltpu.VMEM((1, bq), jnp.float32),
        ],
        interpret=interpret,
        **_grid_params(interpret),
    )(qo, ko, qp, kp, vp)
    out = jnp.swapaxes(out_t, 1, 2)[:, :sq, :d]
    return out, lse[:, 0, :sq]


def _bwd_pallas(q, k, v, do, lse, c, q_offset, k_offset, *, causal,
                block_q, block_k, interpret, window):
    bh, sq, d = q.shape
    sk = k.shape[1]
    scale = 1.0 / np.sqrt(d)
    bq, bk = _blocks(sq, sk, block_q, block_k)
    qp = _pad_to(_pad_to(q, 1, bq), 2, _LANE)
    kp = _pad_to(_pad_to(k, 1, bk), 2, _LANE)
    vp = _pad_to(_pad_to(v, 1, bk), 2, _LANE)
    dop = _pad_to(_pad_to(do, 1, bq), 2, _LANE)
    # padded q rows: lse=_NEG there would make exp(s-lse) explode for
    # in-range k; force a huge lse so p underflows to 0 on padding
    lsep = _pad_to(lse, 1, bq)
    if lsep.shape[1] != sq:
        pad_rows = (
            jax.lax.broadcasted_iota(jnp.int32, lsep.shape, 1) >= sq
        )
        lsep = jnp.where(pad_rows, -_NEG, lsep)
    cp = _pad_to(c, 1, bq)
    # stat vectors enter the kernels sublane-tiled: [BH, 8, Sq] (row 0 live)
    lsep = jnp.broadcast_to(lsep[:, None, :], (bh, _SUBLANE, lsep.shape[1]))
    cp = jnp.broadcast_to(cp[:, None, :], (bh, _SUBLANE, cp.shape[1]))
    dp_ = qp.shape[2]
    nq, nk = qp.shape[1] // bq, kp.shape[1] // bk
    qo = q_offset.astype(jnp.int32).reshape(1, 1)
    ko = k_offset.astype(jnp.int32).reshape(1, 1)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    qspec = pl.BlockSpec((1, bq, dp_), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, bk, dp_), lambda b, i, j: (b, j, 0))
    vec_q = pl.BlockSpec((1, _SUBLANE, bq), lambda b, i, j: (b, 0, i))
    dq_t = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, scale=scale, nk=nk, k_len=sk,
            block_q=bq, block_k=bk, window=window,
        ),
        grid=(bh, nq, nk),
        in_specs=[smem, smem, qspec, kspec, kspec, qspec, vec_q, vec_q],
        out_specs=pl.BlockSpec((1, dp_, bq), lambda b, i, j: (b, 0, i)),
        out_shape=jax.ShapeDtypeStruct((bh, dp_, qp.shape[1]), q.dtype),
        scratch_shapes=[pltpu.VMEM((dp_, bq), jnp.float32)],
        interpret=interpret,
        **_grid_params(interpret),
    )(qo, ko, qp, kp, vp, dop, lsep, cp)
    # dkv: k blocks outer (parallel), q blocks inner (accumulated)
    qspec2 = pl.BlockSpec((1, bq, dp_), lambda b, j, i: (b, i, 0))
    kspec2 = pl.BlockSpec((1, bk, dp_), lambda b, j, i: (b, j, 0))
    vec_q2 = pl.BlockSpec((1, _SUBLANE, bq), lambda b, j, i: (b, 0, i))
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, scale=scale, nq=nq, k_len=sk,
            block_q=bq, block_k=bk, window=window,
        ),
        grid=(bh, nk, nq),
        in_specs=[smem, smem, qspec2, kspec2, kspec2, qspec2, vec_q2, vec_q2],
        out_specs=(
            pl.BlockSpec((1, bk, dp_), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, dp_), lambda b, j, i: (b, j, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, kp.shape[1], dp_), k.dtype),
            jax.ShapeDtypeStruct((bh, kp.shape[1], dp_), v.dtype),
        ),
        scratch_shapes=[
            pltpu.VMEM((bk, dp_), jnp.float32),
            pltpu.VMEM((bk, dp_), jnp.float32),
        ],
        interpret=interpret,
        **_grid_params(interpret),
    )(qo, ko, qp, kp, vp, dop, lsep, cp)
    dq = jnp.swapaxes(dq_t, 1, 2)[:, :sq, :d]
    return dq, dk[:, :sk, :d], dv[:, :sk, :d]


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10)
)
def _flash(q, k, v, q_offset, k_offset, causal, block_q, block_k,
           use_pallas, interpret, window):
    if use_pallas:
        return _fwd_pallas(
            q, k, v, q_offset, k_offset, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            window=window,
        )
    return flash_attention_ref(
        q, k, v, q_offset, k_offset, causal=causal, window=window
    )


def _flash_fwd(q, k, v, q_offset, k_offset, causal, block_q, block_k,
               use_pallas, interpret, window):
    out, lse = _flash(
        q, k, v, q_offset, k_offset, causal, block_q, block_k,
        use_pallas, interpret, window,
    )
    return (out, lse), (q, k, v, out, lse, q_offset, k_offset)


def _flash_bwd(causal, block_q, block_k, use_pallas, interpret, window,
               res, ct):
    q, k, v, out, lse, q_offset, k_offset = res
    do, dlse = ct
    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # [BH, Sq]
    dlse32 = (
        jnp.zeros_like(delta) if dlse is None else dlse.astype(jnp.float32)
    )
    # d s = p * (dp - delta + dlse); fold into one lane vector
    c = delta - dlse32
    if use_pallas:
        dq, dk, dv = _bwd_pallas(
            q, k, v, do, lse, c, q_offset, k_offset, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
            window=window,
        )
    else:
        scale = 1.0 / np.sqrt(q.shape[-1])
        s = jnp.einsum(
            "bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * scale
        if causal:
            qp_ = q_offset + jnp.arange(q.shape[1])
            kp_ = k_offset + jnp.arange(k.shape[1])
            keep = qp_[:, None] >= kp_[None, :]
            if window is not None:
                keep &= (qp_[:, None] - kp_[None, :]) < window
            s = jnp.where(keep[None], s, _NEG)
        p = jnp.exp(s - lse[..., None])
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        dp = jnp.einsum("bqd,bkd->bqk", do32, v.astype(jnp.float32))
        ds = p * (dp - c[..., None]) * scale
        dq = jnp.einsum("bqk,bkd->bqd", ds, k.astype(jnp.float32))
        dk = jnp.einsum("bqk,bqd->bkd", ds, q.astype(jnp.float32))
        dv = jnp.einsum("bqk,bqd->bkd", p, do32)
    z = np.zeros((), jax.dtypes.float0)  # int offsets: symbolic-zero tangent
    return (
        dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype), z, z
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    q_offset=0,
    k_offset=0,
    block_q: int = 512,
    block_k: int = 512,
    use_pallas: Optional[bool] = None,
    interpret: Optional[bool] = None,
    with_lse: bool = False,
    window: Optional[int] = None,
):
    """Blockwise exact attention over [BH, S, D] head-major arrays.

    Default blocking is 512x512 BY MEASUREMENT on v5e (BENCH_ONCHIP.md
    2026-07-31, the 04:14 train blocksweep + 04:24 fwd blocksweep): at
    s=8192/d=64/bf16 the 128x128 blocks ran fwd at 4657.6 and train at
    8527.5 GFLOP/s; 512x512 runs 7715.7 fwd (1.66x) and 12997.6 train
    (1.52x) — fewer grid steps and longer MXU contractions beat the
    smaller working set. Blocks clamp to the sequence length (short
    callers unaffected) and, in window mode, to the window scale (the
    whole-block skip contract below).

    ``q_offset``/``k_offset`` are the GLOBAL sequence positions of row 0
    (traced values allowed — ring attention passes ``axis_index``-derived
    offsets), so causal masking is correct on sequence-sharded chunks.
    ``window`` (requires causal) restricts each query to the ``window``
    most recent keys (0 <= q_pos - k_pos < window — sliding-window /
    local attention); out-of-window BLOCKS are skipped entirely, so
    compute per query is O(window), not O(S).
    Returns ``out`` or ``(out, lse)`` — lse is what chunk-merging needs.
    """
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (sliding window)")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        window = int(window)
        # the O(window)-per-query contract rests on whole-block skips
        # (_block_live): a 512-wide block is never fully outside a 256
        # window, so the large default blocking would compute ~2 extra
        # block-widths of masked work per query row. Clamp blocks to the
        # window scale (pow2, floor 128 — the sweep's win came from
        # fewer grid steps, which small windows cap anyway).
        cap = max(128, 1 << (window - 1).bit_length())
        block_q = min(block_q, cap)
        block_k = min(block_k, cap)
    if use_pallas is None:
        use_pallas = _use_pallas() and pl is not None
    if interpret is None:
        interpret = not _use_pallas()
    q_offset = jnp.asarray(q_offset, jnp.int32)
    k_offset = jnp.asarray(k_offset, jnp.int32)
    out, lse = _flash(
        q, k, v, q_offset, k_offset, causal, block_q, block_k,
        bool(use_pallas), bool(interpret), window,
    )
    return (out, lse) if with_lse else out


def flash_mha(
    x_q: jax.Array,
    x_k: jax.Array,
    x_v: jax.Array,
    n_heads: int,
    *,
    causal: bool = False,
    q_offset=0,
    k_offset=0,
    use_pallas: Optional[bool] = None,
    interpret: Optional[bool] = None,
    window: Optional[int] = None,
    n_kv_heads: Optional[int] = None,
) -> jax.Array:
    """Multi-head wrapper: [B, S, H] with H = n_heads * dh, like dense_mha.

    ``n_kv_heads`` (grouped-query attention): K/V carry only that many
    heads (``x_k``/``x_v`` are [B, S, n_kv_heads * dh]) and each K/V head
    serves ``n_heads // n_kv_heads`` query heads — the KV-cache/bandwidth
    reduction of GQA/MQA (n_kv_heads=1). The kernel itself is unchanged:
    K/V heads are broadcast to the query-head grouping at the wrapper."""
    b, sq, h = x_q.shape
    sk = x_k.shape[1]
    dh = h // n_heads
    kvh = n_kv_heads if n_kv_heads is not None else n_heads
    if n_heads % kvh:
        raise ValueError(f"n_heads={n_heads} must divide by n_kv_heads={kvh}")

    def split(x, s, nh):
        return (
            x.reshape(b, s, nh, dh)
            .transpose(0, 2, 1, 3)
            .reshape(b * nh, s, dh)
        )

    def expand_kv(x):  # [B*kvh, S, dh] -> [B*n_heads, S, dh] (group repeat)
        x = x.reshape(b, kvh, sk, dh)
        x = jnp.repeat(x, n_heads // kvh, axis=1)
        return x.reshape(b * n_heads, sk, dh)

    out = flash_attention(
        split(x_q, sq, n_heads),
        expand_kv(split(x_k, sk, kvh)),
        expand_kv(split(x_v, sk, kvh)),
        causal=causal, q_offset=q_offset, k_offset=k_offset,
        use_pallas=use_pallas, interpret=interpret, window=window,
    )
    return (
        out.reshape(b, n_heads, sq, dh).transpose(0, 2, 1, 3).reshape(b, sq, h)
    )
