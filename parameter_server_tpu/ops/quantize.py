"""Stochastic fixed-point quantization — Pallas TPU kernel.

Device-side half of the fixing_float filter (ref src/filter/fixing_float.h):
compress push payloads to uint8/uint16 with stochastic rounding before they
cross chips, decompress after. The kernel fuses min/max-normalize +
add-noise + floor in VMEM using the on-core PRNG; outside TPU the jnp
reference path (filter/fixing_float.quantize_jax) is used.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _use_pallas() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def use_pallas() -> bool:
    """Public switch: True when the TPU kernel path is active (the
    production push/pull wires key off this, async_sgd.make_push_reduce)."""
    return _use_pallas()


_LANES = 128
_SUBLANES = 8
_TILE = _LANES * _SUBLANES


def _kernel(x_ref, lo_ref, hi_ref, seed_ref, out_ref, *, levels):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    # fold the grid position into the seed: every block must draw its OWN
    # noise, not replay block 0's stream (block-correlated rounding noise
    # is biased in aggregate)
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    x = x_ref[:]
    lo = lo_ref[0]
    hi = hi_ref[0]
    scaled = (x - lo) / (hi - lo) * levels
    bits = pltpu.bitcast(pltpu.prng_random_bits(x.shape), jnp.uint32)
    # uniform [0,1) noise from the top 24 bits (mosaic lacks uint32->f32;
    # the value fits int32, so route the cast through it)
    noise = (bits >> 8).astype(jnp.int32).astype(jnp.float32) * (1.0 / (1 << 24))
    q = jnp.clip(jnp.floor(scaled + noise), 0.0, levels)
    out_ref[:] = q


def quantize_traced(x: jax.Array, seed, *, num_bytes: int = 1):
    """Traceable quantize for use INSIDE jitted/shard_mapped steps (the
    production push/pull wire, async_sgd.make_push_reduce): ``seed`` is a
    traced int32 scalar. On TPU this lowers to the fused Pallas kernel;
    elsewhere to the jnp reference chain."""
    from ..filter.fixing_float import quantize_jax

    if not _use_pallas():
        key = jax.random.fold_in(
            jax.random.PRNGKey(0x9A17), jnp.asarray(seed, jnp.uint32)
        )
        return quantize_jax(x, num_bytes, key)
    return _quantize_pallas(x, jnp.asarray(seed, jnp.int32), num_bytes)


def _quantize_pallas(x: jax.Array, seed, num_bytes: int):
    levels = float((1 << (8 * num_bytes)) - 1)
    lo = jnp.min(x)
    hi = jnp.maximum(jnp.max(x), lo + 1e-12)
    dt = jnp.uint8 if num_bytes == 1 else jnp.uint16
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = x.shape[0]
    # big blocks (same lesson as ops/ftrl.py): an (8,128) block makes the
    # grid enormous on multi-M-slot shards and grid overhead dominates.
    # Large arrays pad up to a whole 2048x128 block (≤1MB of padding —
    # lo/hi come from the UNpadded x, and padded tail rows are sliced
    # off) so non-power-of-two shard sizes still run big blocks; small
    # arrays fall back to the largest power-of-two divisor.
    block_rows = 2048
    if n >= _LANES * block_rows:
        pad = (-n) % (_LANES * block_rows)
    else:
        pad = (-n) % _TILE
    xp = jnp.pad(x, (0, pad)).reshape(-1, _LANES)
    rows = xp.shape[0]
    while rows % block_rows:
        block_rows //= 2
    spec = pl.BlockSpec(
        (block_rows, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    q = pl.pallas_call(
        functools.partial(_kernel, levels=levels),
        grid=(rows // block_rows,),
        out_shape=jax.ShapeDtypeStruct(xp.shape, jnp.float32),
        in_specs=[
            spec,
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=spec,
    )(
        xp,
        lo.reshape(1),
        hi.reshape(1),
        seed.reshape(1),
    )
    return q.reshape(-1)[:n].astype(dt), lo, hi


@functools.partial(jax.jit, static_argnames=("num_bytes", "force_pallas"))
def quantize(x: jax.Array, seed, *, num_bytes: int = 1, force_pallas: bool = False):
    """Quantize a 1-D float array to n-byte fixed point.

    Returns (q, lo, hi); q is uint8/uint16. Padding to the TPU tile is
    handled internally.
    """
    from ..filter.fixing_float import quantize_jax

    if not (force_pallas or _use_pallas()):
        return quantize_jax(x, num_bytes, jax.random.PRNGKey(seed))
    return _quantize_pallas(x, jnp.asarray(seed, jnp.int32), num_bytes)


def dequantize(q: jax.Array, lo, hi, num_bytes: int = 1) -> jax.Array:
    from ..filter.fixing_float import dequantize_jax

    return dequantize_jax(q, lo, hi, num_bytes)
