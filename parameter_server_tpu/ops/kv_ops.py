"""Sharded key-value pull/push — the device data plane.

This is where the reference's ``KVVector::Push/Pull`` message traffic
(kv_vector.h + van.cc sends) becomes XLA collectives over the mesh:

- **pull**: every (data, server) device gathers the slots it owns for the
  requested indices, then a ``psum`` over the *server* axis assembles full
  rows (each slot is owned by exactly one server shard, so summation is
  assembly). Cross-chip traffic rides ICI, sized ``n_idx × k`` — the same
  payload the reference puts on the wire, minus serialization.
- **push**: per-worker values are first combined across the *data* axis
  (``psum`` — gradient aggregation, the reference's server-side merge of
  worker messages), then every server shard scatter-adds the entries whose
  slot falls in its key range. Duplicate indices within a request
  scatter-add correctly (segment aggregation).

All shapes are static: indices are int32 slot ids produced by the host-side
localizer/directory; out-of-range or padding entries use slot id ``P``
(one-past-the-end sentinel) and are dropped by range masking.

**Donation (the zero-copy data plane).** ``push``/``push_pull`` come in
two flavors per update: the plain entry points leave the input table
alive (XLA materializes a fresh ``[P, k]`` output — a full HBM table
copy per push), and the ``*_donated`` entry points alias input→output
(``donate_argnums``) so the scatter-add happens in place. Callers that
OWN their table (KVVector/KVMap channel tables, staged push buffers)
use the donated path; anyone still holding the input array afterwards
gets jax's read-after-donate ``RuntimeError`` rather than silent
staleness. Checkpoint/replica paths must therefore copy BEFORE the
push dispatches — see doc/PERFORMANCE.md "Donation rules".

``push_pull`` fuses the reference's server-side "aggregate then reply"
round trip (push message + pull reply) into ONE dispatched program:
scatter-add, then gather from the freshly-updated shard, bit-identical
to ``push`` followed by ``pull``.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import shard_map

from ..parallel.mesh import DATA_AXIS, SERVER_AXIS
from ..parallel.partition import BATCH_SPEC, REPLICATED_SPEC, TABLE_SPEC
from ..telemetry import device as _device
from ..telemetry.instruments import cached_kvops_instruments as _tel


def index_spec(batch_sharded: bool) -> P:
    """The slot-index spec: per-worker key sets ride the data axis,
    replicated otherwise (spec constants owned by parallel/partition.py
    — the declarative home of every layout here)."""
    return BATCH_SPEC if batch_sharded else REPLICATED_SPEC


def localize(idx: jnp.ndarray, shard: int):
    """Shard-relative index + ownership mask for this server's key range.

    Computes ``lo = axis_index(server) * shard`` internally, so it must be
    called inside a ``shard_map`` over SERVER_AXIS. int32-safe up to
    ``shard == 2**31``: a single-server 2^31-slot table's ids occupy the
    whole non-negative int32 lattice, but the Python constant ``2**31``
    overflows jnp's operand parsing (jnp ops are jitted; an int operand
    above int32max raises OverflowError before tracing), so the one-shard
    case short-circuits to ``lo = 0`` and masks sentinels by sign alone —
    any padding/foreign id is negative there (see ``slot_sentinel``).
    """
    if shard > (1 << 31):
        raise ValueError(
            f"shard of {shard} slots exceeds int32 slot ids; "
            "spread the table over more server shards"
        )
    if shard == (1 << 31):
        ok = idx >= 0
        return jnp.clip(idx, 0, (1 << 31) - 1), ok
    lo = jax.lax.axis_index(SERVER_AXIS) * shard
    rel = idx - lo
    ok = (rel >= 0) & (rel < shard)
    return jnp.clip(rel, 0, shard - 1), ok


def slot_sentinel(num_slots: int) -> int:
    """Padding slot id for host-side preps: one-past-the-end when that
    fits int32 (the documented sentinel), else -1 — a 2^31-slot table's
    ``num_slots`` overflows np.int32, and any un-owned id works because
    every shard's ownership mask (``localize``) drops it."""
    return num_slots if num_slots < (1 << 31) else -1


def valid_slots(slots: jnp.ndarray, num_slots: int) -> jnp.ndarray:
    """Mask of non-sentinel slot ids, int32-safe at ``num_slots == 2**31``
    (where the sentinel is -1 and the comparison against ``num_slots``
    would overflow operand parsing)."""
    if num_slots >= (1 << 31):
        return slots >= 0
    return slots < num_slots


def _pull_impl(table, idx, *, mesh: Mesh, batch_sharded: bool = True):
    p_total, _ = table.shape
    n_server = mesh.shape[SERVER_AXIS]
    shard = p_total // n_server
    idx_spec = index_spec(batch_sharded)

    def local(tbl, ix):
        rel, ok = localize(ix, shard)
        vals = jnp.where(ok[:, None], tbl[rel], 0)
        return jax.lax.psum(vals, SERVER_AXIS)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(TABLE_SPEC, idx_spec),
        out_specs=idx_spec,
    )(table, idx)


# no-donate: pull reads the table; the store keeps serving it afterwards.
# Every public entry point below is wrapped into the device inventory
# (telemetry/device.py): each lower().compile() lands its cost/memory
# analysis in the ``device`` bench section, recompiles are counted per
# name, and the donated paths' aliasing is runtime-verified.
pull = _device.instrument(
    "kv_pull",
    # no-donate: pull reads the table; the store keeps serving it
    functools.partial(jax.jit, static_argnames=("mesh", "batch_sharded"))(
        _pull_impl
    ),
    static_argnames=("mesh", "batch_sharded"),
)
pull.__doc__ = """Gather rows ``table[idx]`` from a server-sharded table.

table: [P, k] sharded P(SERVER, None); idx: [n] int32, sharded over DATA
if batch_sharded (each worker pulls its own key set — the common case)
else replicated. Returns [n, k] with the same batch sharding.
"""


def _push_local_fn(shard, n_data, average, combined):
    """Per-shard push body shared by push and push_pull (bit-identical
    aggregation between the plain and fused dispatches)."""

    def local(tbl, ix, v):
        if combined:
            ix = jax.lax.all_gather(ix, DATA_AXIS, tiled=True)
            v = jax.lax.all_gather(v, DATA_AXIS, tiled=True)
        if average and combined:
            # average only when contributions were actually combined
            v = v / n_data
        rel, ok = localize(ix, shard)
        v = jnp.where(ok[:, None], v, 0)
        return tbl.at[rel].add(v, mode="drop")

    return local


def _push_impl(
    table,
    idx,
    vals,
    *,
    mesh: Mesh,
    batch_sharded: bool = True,
    average: bool = False,
    combine_data: bool = True,
):
    p_total, k = table.shape
    n_server = mesh.shape[SERVER_AXIS]
    n_data = mesh.shape[DATA_AXIS]
    shard = p_total // n_server
    idx_spec = index_spec(batch_sharded)
    combined = batch_sharded and combine_data and n_data > 1
    local = _push_local_fn(shard, n_data, average, combined)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(TABLE_SPEC, idx_spec, idx_spec),
        out_specs=TABLE_SPEC,
    )(table, idx, vals)


_PUSH_STATICS = ("mesh", "batch_sharded", "average", "combine_data")

# no-donate: the copying path — for callers whose input table must
# survive the push (checkpoint staging, A/B benches); owners use
# push_donated
push = _device.instrument(
    "kv_push",
    # no-donate: the copying path — for callers whose input table must
    # survive the push (checkpoint staging, A/B benches)
    functools.partial(jax.jit, static_argnames=_PUSH_STATICS)(_push_impl),
    static_argnames=_PUSH_STATICS,
)
push.__doc__ = """Scatter-add ``vals`` at ``idx`` into the server-sharded table.

table: [P, k] sharded P(SERVER, None); idx: [n] int32; vals: [n, k].
With batch_sharded, each worker contributes its own (idx, vals): entries
are all-gathered over the DATA axis so every server shard sees every
contribution (the reference's sliced push messages to each server).
``average`` divides by the worker count (scaled gradient aggregation).

This entry point COPIES: XLA materializes a fresh table output. Callers
that own their table should use :func:`push_donated` (in-place).
"""

_push_donated_jit = _device.instrument(
    "kv_push_donated",
    functools.partial(
        jax.jit, static_argnames=_PUSH_STATICS, donate_argnums=(0,)
    )(_push_impl),
    static_argnames=_PUSH_STATICS,
    donate_argnums=(0,),
)


def push_donated(table, idx, vals, **kw):
    """In-place :func:`push`: the input table buffer is DONATED to the
    update (XLA aliases input→output; no ``[P, k]`` copy). The caller
    must own ``table`` exclusively — any other live reference to it
    raises on next use (read-after-donate). Same math as ``push``."""
    tel = _tel()
    if tel is not None:
        tel["donated_pushes"].inc()
    return _push_donated_jit(table, idx, vals, **kw)


def _push_pull_impl(
    table,
    idx,
    vals,
    pull_idx,
    *,
    mesh: Mesh,
    batch_sharded: bool = True,
    average: bool = False,
    combine_data: bool = True,
):
    p_total, k = table.shape
    n_server = mesh.shape[SERVER_AXIS]
    n_data = mesh.shape[DATA_AXIS]
    shard = p_total // n_server
    idx_spec = index_spec(batch_sharded)
    combined = batch_sharded and combine_data and n_data > 1
    push_local = _push_local_fn(shard, n_data, average, combined)

    def local(tbl, ix, v, pix):
        new = push_local(tbl, ix, v)
        rel, ok = localize(pix, shard)
        out = jnp.where(ok[:, None], new[rel], 0)
        return new, jax.lax.psum(out, SERVER_AXIS)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(TABLE_SPEC, idx_spec, idx_spec, idx_spec),
        out_specs=(TABLE_SPEC, idx_spec),
    )(table, idx, vals, pull_idx)


# no-donate: the copying fused path (A/B benches, shared-table callers)
_push_pull_jit = _device.instrument(
    "kv_push_pull",
    # no-donate: the copying fused path (A/B benches, shared tables)
    functools.partial(
        jax.jit, static_argnames=_PUSH_STATICS
    )(_push_pull_impl),
    static_argnames=_PUSH_STATICS,
)
_push_pull_donated_jit = _device.instrument(
    "kv_push_pull_donated",
    functools.partial(
        jax.jit, static_argnames=_PUSH_STATICS, donate_argnums=(0,)
    )(_push_pull_impl),
    static_argnames=_PUSH_STATICS,
    donate_argnums=(0,),
)


def _dispatch_fused(jit_fn, table, idx, vals, pull_idx, kw):
    if pull_idx is None:
        pull_idx = idx
    tel = _tel()
    if tel is None:
        return jit_fn(table, idx, vals, pull_idx, **kw)
    t0 = time.perf_counter()
    out = jit_fn(table, idx, vals, pull_idx, **kw)
    # dispatch wall time (host side), not device completion — the win
    # this kernel buys is one launch instead of two
    tel["fused_dispatch"].observe(time.perf_counter() - t0)
    return out


def push_pull(table, idx, vals, pull_idx=None, **kw):
    """Fused scatter-add + gather in ONE dispatched program: returns
    ``(new_table, pulled)`` where ``pulled = pull(push(table, idx, vals),
    pull_idx)`` bit-for-bit. ``pull_idx`` defaults to ``idx`` (the
    common push→pull-same-keys round trip — the reference's server-side
    "aggregate then reply" in one launch). This entry point copies the
    table; owners use :func:`push_pull_donated`."""
    return _dispatch_fused(_push_pull_jit, table, idx, vals, pull_idx, kw)


def push_pull_donated(table, idx, vals, pull_idx=None, **kw):
    """:func:`push_pull` with the table donated (in-place update, no
    ``[P, k]`` copy). Caller must own ``table`` exclusively."""
    tel = _tel()
    if tel is not None:
        tel["donated_pushes"].inc()
    return _dispatch_fused(
        _push_pull_donated_jit, table, idx, vals, pull_idx, kw
    )


def scatter_grad_dense(
    idx: jax.Array, vals: jax.Array, p_total: int, k: int
) -> jax.Array:
    """Densify a sparse push into a [P, k] gradient table (single-shard
    helper used by fused learner steps; padding slot P drops)."""
    g = jnp.zeros((p_total, k), vals.dtype)
    return g.at[idx].add(vals, mode="drop")
