"""Sharded key-value pull/push — the device data plane.

This is where the reference's ``KVVector::Push/Pull`` message traffic
(kv_vector.h + van.cc sends) becomes XLA collectives over the mesh:

- **pull**: every (data, server) device gathers the slots it owns for the
  requested indices, then a ``psum`` over the *server* axis assembles full
  rows (each slot is owned by exactly one server shard, so summation is
  assembly). Cross-chip traffic rides ICI, sized ``n_idx × k`` — the same
  payload the reference puts on the wire, minus serialization.
- **push**: per-worker values are first combined across the *data* axis
  (``psum`` — gradient aggregation, the reference's server-side merge of
  worker messages), then every server shard scatter-adds the entries whose
  slot falls in its key range. Duplicate indices within a request
  scatter-add correctly (segment aggregation).

All shapes are static: indices are int32 slot ids produced by the host-side
localizer/directory; out-of-range or padding entries use slot id ``P``
(one-past-the-end sentinel) and are dropped by range masking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..utils.compat import shard_map

from ..parallel.mesh import DATA_AXIS, SERVER_AXIS


def localize(idx: jnp.ndarray, shard: int):
    """Shard-relative index + ownership mask for this server's key range.

    Computes ``lo = axis_index(server) * shard`` internally, so it must be
    called inside a ``shard_map`` over SERVER_AXIS. int32-safe up to
    ``shard == 2**31``: a single-server 2^31-slot table's ids occupy the
    whole non-negative int32 lattice, but the Python constant ``2**31``
    overflows jnp's operand parsing (jnp ops are jitted; an int operand
    above int32max raises OverflowError before tracing), so the one-shard
    case short-circuits to ``lo = 0`` and masks sentinels by sign alone —
    any padding/foreign id is negative there (see ``slot_sentinel``).
    """
    if shard > (1 << 31):
        raise ValueError(
            f"shard of {shard} slots exceeds int32 slot ids; "
            "spread the table over more server shards"
        )
    if shard == (1 << 31):
        ok = idx >= 0
        return jnp.clip(idx, 0, (1 << 31) - 1), ok
    lo = jax.lax.axis_index(SERVER_AXIS) * shard
    rel = idx - lo
    ok = (rel >= 0) & (rel < shard)
    return jnp.clip(rel, 0, shard - 1), ok


def slot_sentinel(num_slots: int) -> int:
    """Padding slot id for host-side preps: one-past-the-end when that
    fits int32 (the documented sentinel), else -1 — a 2^31-slot table's
    ``num_slots`` overflows np.int32, and any un-owned id works because
    every shard's ownership mask (``localize``) drops it."""
    return num_slots if num_slots < (1 << 31) else -1


def valid_slots(slots: jnp.ndarray, num_slots: int) -> jnp.ndarray:
    """Mask of non-sentinel slot ids, int32-safe at ``num_slots == 2**31``
    (where the sentinel is -1 and the comparison against ``num_slots``
    would overflow operand parsing)."""
    if num_slots >= (1 << 31):
        return slots >= 0
    return slots < num_slots


@functools.partial(jax.jit, static_argnames=("mesh", "batch_sharded"))
def pull(table: jax.Array, idx: jax.Array, *, mesh: Mesh, batch_sharded: bool = True):
    """Gather rows ``table[idx]`` from a server-sharded table.

    table: [P, k] sharded P(SERVER, None); idx: [n] int32, sharded over DATA
    if batch_sharded (each worker pulls its own key set — the common case)
    else replicated. Returns [n, k] with the same batch sharding.
    """
    p_total, _ = table.shape
    n_server = mesh.shape[SERVER_AXIS]
    shard = p_total // n_server
    idx_spec = P(DATA_AXIS) if batch_sharded else P()

    def local(tbl, ix):
        rel, ok = localize(ix, shard)
        vals = jnp.where(ok[:, None], tbl[rel], 0)
        return jax.lax.psum(vals, SERVER_AXIS)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(SERVER_AXIS, None), idx_spec),
        out_specs=idx_spec,
    )(table, idx)


@functools.partial(
    jax.jit, static_argnames=("mesh", "batch_sharded", "average", "combine_data")
)
def push(
    table: jax.Array,
    idx: jax.Array,
    vals: jax.Array,
    *,
    mesh: Mesh,
    batch_sharded: bool = True,
    average: bool = False,
    combine_data: bool = True,
):
    """Scatter-add ``vals`` at ``idx`` into the server-sharded table.

    table: [P, k] sharded P(SERVER, None); idx: [n] int32; vals: [n, k].
    With batch_sharded, each worker contributes its own (idx, vals): entries
    are all-gathered over the DATA axis so every server shard sees every
    contribution (the reference's sliced push messages to each server).
    ``average`` divides by the worker count (scaled gradient aggregation).
    """
    p_total, k = table.shape
    n_server = mesh.shape[SERVER_AXIS]
    n_data = mesh.shape[DATA_AXIS]
    shard = p_total // n_server
    idx_spec = P(DATA_AXIS) if batch_sharded else P()

    combined = batch_sharded and combine_data and n_data > 1

    def local(tbl, ix, v):
        if combined:
            ix = jax.lax.all_gather(ix, DATA_AXIS, tiled=True)
            v = jax.lax.all_gather(v, DATA_AXIS, tiled=True)
        if average and combined:
            # average only when contributions were actually combined
            v = v / n_data
        rel, ok = localize(ix, shard)
        v = jnp.where(ok[:, None], v, 0)
        return tbl.at[rel].add(v, mode="drop")

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(SERVER_AXIS, None), idx_spec, idx_spec),
        out_specs=P(SERVER_AXIS, None),
    )(table, idx, vals)


def scatter_grad_dense(
    idx: jax.Array, vals: jax.Array, p_total: int, k: int
) -> jax.Array:
    """Densify a sparse push into a [P, k] gradient table (single-shard
    helper used by fused learner steps; padding slot P drops)."""
    g = jnp.zeros((p_total, k), vals.dtype)
    return g.at[idx].add(vals, mode="drop")
