"""HTTP exposition: /metrics, /healthz, /debug/snapshot.

The reference renders cluster health into a scheduler-side dashboard
(``src/system/dashboard.cc``); production systems scrape. This module
is the scrape point: a stdlib ``http.server`` daemon (no dependencies,
port 0 test-friendly, clean join on shutdown) serving

- ``/metrics`` — Prometheus text of the node-labeled cluster aggregate
  (telemetry/aggregate.py), text-format escaping included;
- ``/metrics/history`` — JSON range query over the history plane
  (telemetry/history.py): ``?name=<metric>[&window=600][&resolution=10]
  [&q=0.99][&labels={"k":"v"}]`` returns this node's ring cells plus
  every shipped per-node ring (staleness disclosed per node);
- ``/healthz`` — JSON heartbeat + recovery-coordinator state; **non-200
  (503)** while any shard is dead or its metric reports are stale;
- ``/debug/snapshot`` — JSON registry export + cluster view + alert
  states + the recent timeline tail, for humans mid-incident;
- ``/debug/bundle`` — a full diagnostic bundle (telemetry/blackbox.py:
  per-node flight-recorder rings with staleness, metrics snapshot,
  alert states, executors, Perfetto trace), floored at the scrape
  refresh interval so hammering it cannot re-drive the message plane.

Wiring is one call: :func:`expose_cluster` stands the endpoint up over
a started Postoffice (aux runtime + metric-report timer + default
alert rules), which is exactly what ``bench.py --expose-port``,
``apps/serve --expose-port`` and ``make metrics-serve`` do.
:class:`ExpositionServer` itself only needs three callables, so tests
(and single-registry processes) can serve anything.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from . import registry as telemetry_registry

#: Prometheus text exposition content type (the 0.0.4 text format)
CONTENT_TYPE_METRICS = "text/plain; version=0.0.4; charset=utf-8"


def _parse_history_query(raw_path: str):
    """``/metrics/history`` query string → (params dict, error string).

    Recognized params: ``name`` (required), ``window`` (seconds,
    default 600), ``resolution`` (seconds, optional — the store snaps
    to the coarsest level that still covers the window otherwise),
    ``q`` (quantile in (0, 1], histograms only), ``labels`` (a JSON
    object; subset match). A malformed value is a 400, not a guess —
    mid-incident a silently-defaulted window is worse than an error.
    """
    from urllib.parse import parse_qs, urlsplit

    try:
        qs = parse_qs(urlsplit(raw_path).query)
    except ValueError as e:
        return None, f"bad query string: {e}"
    name = (qs.get("name") or [""])[0].strip()
    if not name:
        return None, "missing required query param: name"
    params: dict = {"name": name, "window_s": 600.0}
    try:
        if "window" in qs:
            params["window_s"] = float(qs["window"][0])
        if "resolution" in qs:
            params["resolution"] = float(qs["resolution"][0])
        if "q" in qs:
            params["q"] = float(qs["q"][0])
    except ValueError as e:
        return None, f"bad numeric query param: {e}"
    if params["window_s"] <= 0:
        return None, "window must be > 0"
    if "labels" in qs:
        try:
            labels = json.loads(qs["labels"][0])
        except ValueError as e:
            return None, f"labels must be a JSON object: {e}"
        if not isinstance(labels, dict):
            return None, "labels must be a JSON object"
        params["labels"] = {str(k): str(v) for k, v in labels.items()}
    return params, None


class ExpositionServer:
    """One daemon HTTP server over three content callables.

    ``metrics_fn() -> str`` (Prometheus text), ``health_fn() ->
    (ok, detail_dict)`` (503 when not ok), ``snapshot_fn() -> dict``
    (JSON). ``history_fn(params) -> dict`` (optional) answers
    ``/metrics/history`` range queries with the parsed query params
    (see :func:`_parse_history_query`); absent → 404. ``port=0`` binds
    an ephemeral port (read :attr:`port` after :meth:`start`);
    :meth:`close` shuts the server down and JOINS the serving thread —
    no leaks for the tier-1 suite's thread guard.
    """

    def __init__(
        self,
        metrics_fn: Callable[[], str],
        health_fn: Optional[Callable[[], Tuple[bool, dict]]] = None,
        snapshot_fn: Optional[Callable[[], dict]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        bundle_fn: Optional[Callable[[], dict]] = None,
        history_fn: Optional[Callable[[dict], dict]] = None,
    ):
        self.metrics_fn = metrics_fn
        self.health_fn = health_fn
        self.snapshot_fn = snapshot_fn
        self.bundle_fn = bundle_fn
        self.history_fn = history_fn
        self.host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --

    def start(self) -> "ExpositionServer":
        if self._httpd is not None:
            return self
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # one scrape is one response; keep-alive would pin handler
            # threads across scrape intervals
            protocol_version = "HTTP/1.0"

            def log_message(self, fmt, *args):  # noqa: N802 — stdlib name
                pass  # scrapes are periodic; stderr spam helps no one

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — stdlib name
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = outer.metrics_fn().encode("utf-8")
                        self._send(200, body, CONTENT_TYPE_METRICS)
                    elif path == "/metrics/history":
                        if outer.history_fn is None:
                            self._send(
                                404, b"no history source\n", "text/plain"
                            )
                            return
                        params, err = _parse_history_query(self.path)
                        if err is not None:
                            self._send(
                                400, (err + "\n").encode(), "text/plain"
                            )
                            return
                        body = (json.dumps(
                            outer.history_fn(params), default=str
                        ) + "\n").encode()
                        self._send(200, body, "application/json")
                    elif path == "/healthz":
                        ok, detail = (
                            outer.health_fn()
                            if outer.health_fn is not None
                            else (True, {"ok": True, "note": "no health source"})
                        )
                        body = (json.dumps(detail, indent=2) + "\n").encode()
                        self._send(
                            200 if ok else 503, body, "application/json"
                        )
                    elif path == "/debug/snapshot":
                        snap = (
                            outer.snapshot_fn()
                            if outer.snapshot_fn is not None
                            else {}
                        )
                        body = (json.dumps(snap, indent=2, default=str)
                                + "\n").encode()
                        self._send(200, body, "application/json")
                    elif path == "/debug/bundle":
                        if outer.bundle_fn is None:
                            self._send(
                                404, b"no bundle source\n", "text/plain"
                            )
                        else:
                            body = (json.dumps(
                                outer.bundle_fn(), default=str
                            ) + "\n").encode()
                            self._send(200, body, "application/json")
                    elif path == "/":
                        body = (
                            b"parameter_server_tpu metrics endpoint\n"
                            b"/metrics /metrics/history?name=<metric> "
                            b"/healthz /debug/snapshot /debug/bundle\n"
                        )
                        self._send(200, body, "text/plain; charset=utf-8")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # noqa: BLE001 — a broken
                    # renderer must answer 500, not hang the scraper
                    body = f"internal error: {type(e).__name__}: {e}\n".encode()
                    try:
                        self._send(500, body, "text/plain")
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((self.host, self._port), Handler)
        # handler threads are daemonic; shutdown() below stops the
        # accept loop and close() joins the serving thread
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
            name="metrics-exposition",
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def serve_registry(
    reg=None, host: str = "127.0.0.1", port: int = 0
) -> ExpositionServer:
    """Minimal endpoint over ONE registry (no cluster plane): /metrics
    renders it directly, /healthz is always ok, /debug/snapshot is its
    snapshot. For single-registry processes and tests."""
    def metrics() -> str:
        r = reg or telemetry_registry.default_registry()
        return r.render_text()

    def snapshot() -> dict:
        r = reg or telemetry_registry.default_registry()
        return {"metrics": r.snapshot()}

    return ExpositionServer(metrics, None, snapshot, host=host, port=port).start()


def _timeline_tail(n: int = 64) -> dict:
    """Last ``n`` span events from the installed JSONL sink (tolerant
    of torn tails), with the sink's state DISCLOSED: an empty events
    list under ``sink: parked`` (a sink exists but is temporarily
    uninstalled — an embedded A/B is running) or ``sink: absent`` (no
    sink was ever installed) means "no trace captured", which is not
    the same claim as "nothing happened"."""
    from . import spans as telemetry_spans

    sink = telemetry_spans.get_sink()
    path = getattr(sink, "path", None)
    tail: dict = {
        "sink": telemetry_spans.sink_state(),
        "path": path,
        "events": [],
    }
    if not path:
        return tail
    try:
        from . import timeline

        tail["events"] = timeline.load_events(path)[-n:]
    except Exception:
        pass
    return tail


def expose_cluster(
    po=None,
    port: int = 0,
    host: str = "127.0.0.1",
    alerts: Optional[object] = None,
    alert_rules: Optional[list] = None,
    metrics_interval: float = 1.0,
    check_interval: float = 0.5,
    heartbeat_timeout: float = 10.0,
    stale_after_s: Optional[float] = None,
    register_nodes: bool = True,
) -> ExpositionServer:
    """Stand the full cluster metrics plane up over a started
    Postoffice: aux runtime (created if absent), every manager node
    registered as a heartbeat sampler, the metric-report timer running,
    the default SLO alert rules evaluating, and the HTTP endpoint
    serving the merged view. Returns the server; ``close_cluster(srv)``
    (or ``srv.close()`` + ``aux.stop()``) tears it down.

    ``alerts`` passes a prebuilt AlertManager; ``alert_rules`` builds
    one from a rule list; neither loads ``configs/alerts/default.json``.
    """
    from ..system.postoffice import Postoffice

    po = po or Postoffice.instance()
    aux = po.start_aux(heartbeat_timeout=heartbeat_timeout)
    if stale_after_s is not None:
        aux.cluster.stale_after_s = stale_after_s
    if register_nodes:
        for node in list(po.manager.nodes):
            aux.register(node.id)
    explicit = alerts is not None or alert_rules is not None
    if alerts is None:
        from .alerts import AlertManager, default_rules

        alerts = AlertManager(
            alert_rules if alert_rules is not None else default_rules()
        )
    # an EXPLICIT manager/rule set always installs (silently keeping
    # the old one would mean the caller's SLO rules never evaluate);
    # the implicit default only fills an empty slot
    if aux.alerts is None or (explicit and aux.alerts is not alerts):
        aux.set_alerts(alerts)
    aux.start(
        check_interval=check_interval, metrics_interval=metrics_interval
    )

    def snapshot() -> dict:
        from . import history as history_mod
        from . import learning as learning_mod

        try:
            hist = {
                "local": history_mod.default_store().snapshot(),
                "cluster": aux.cluster.history_snapshot(),
            }
        except Exception as e:  # noqa: BLE001 — the snapshot must
            # render even if the history plane is mid-teardown
            hist = {"error": f"{type(e).__name__}: {e}"}
        return {
            "node_id": aux.node_id,
            "metrics": telemetry_registry.default_registry().snapshot(),
            "cluster": aux.cluster.snapshot(),
            "alerts": aux.alerts.snapshot() if aux.alerts else None,
            "health": aux.health()[1],
            # the learning truth plane per worker: staleness vs τ,
            # shard shares + imbalance, the top-k hot-slot table,
            # divergence accounting (doc/OBSERVABILITY.md "Learning
            # truth plane")
            "learning": learning_mod.snapshot_all(),
            # retention config + ring occupancy for this node, plus
            # per-node shipped-ring ages (doc/OBSERVABILITY.md
            # "History plane")
            "history": hist,
            "timeline_tail": _timeline_tail(),
        }

    def history_query(params: dict) -> dict:
        from . import history as history_mod

        store = history_mod.default_store()
        store.fold()  # capture the open second before answering
        local = store.query(
            params["name"],
            labels=params.get("labels"),
            window_s=params["window_s"],
            resolution=params.get("resolution"),
            q=params.get("q"),
        )
        cluster = aux.cluster.history_query(
            params["name"],
            labels=params.get("labels"),
            window_s=params["window_s"],
        )
        return {
            "query": params,
            "local": local,
            "nodes": cluster["nodes"],
        }

    srv = ExpositionServer(
        aux.metrics_text,
        aux.health,
        snapshot,
        host=host,
        port=port,
        bundle_fn=aux.bundle,
        history_fn=history_query,
    ).start()
    srv.aux = aux  # for close_cluster / callers that need the runtime
    return srv


def close_cluster(srv: Optional[ExpositionServer]) -> None:
    """Tear down an :func:`expose_cluster` server + its aux runtime
    (idempotent, None-safe — bench teardown paths call it from finally
    blocks)."""
    if srv is None:
        return
    srv.close()
    aux = getattr(srv, "aux", None)
    if aux is not None:
        aux.stop()


def _demo_main(argv=None) -> int:
    """``make metrics-serve``: a tiny live system (CPU mesh, synthetic
    linear training ticking in the background) with the full metrics
    plane exposed — scrape http://127.0.0.1:<port>/metrics while it
    runs. Ctrl-C (or --duration) stops it cleanly."""
    import argparse
    import time

    ap = argparse.ArgumentParser(description=_demo_main.__doc__)
    ap.add_argument("--port", type=int, default=9100)
    ap.add_argument("--duration", type=float, default=0.0,
                    help="seconds to serve (0 = until Ctrl-C)")
    ap.add_argument("--steps-per-tick", type=int, default=4)
    args = ap.parse_args(argv)

    import numpy as np

    from ..apps.linear.async_sgd import AsyncSGDWorker
    from ..apps.linear.config import (
        Config,
        LearningRateConfig,
        PenaltyConfig,
        SGDConfig,
    )
    from ..system.postoffice import Postoffice
    from ..utils.sparse import random_sparse

    Postoffice.reset()
    po = Postoffice.instance().start()
    srv = expose_cluster(po, port=args.port, metrics_interval=1.0)
    conf = Config()
    conf.penalty = PenaltyConfig(type="l1", lambda_=[0.01])
    conf.learning_rate = LearningRateConfig(type="decay", alpha=0.5, beta=1.0)
    conf.async_sgd = SGDConfig(
        algo="ftrl", minibatch=512, num_slots=1 << 12, max_delay=1
    )
    worker = AsyncSGDWorker(conf, mesh=po.mesh, name="metrics_demo")
    rng = np.random.default_rng(0)
    w_true = (rng.normal(size=1 << 12) * (rng.random(1 << 12) < 0.2)).astype(
        np.float32
    )
    print(f"metrics:  {srv.url}/metrics")
    print(f"healthz:  {srv.url}/healthz")
    print(f"snapshot: {srv.url}/debug/snapshot")
    t_end = time.monotonic() + args.duration if args.duration > 0 else None
    i = 0
    try:
        while t_end is None or time.monotonic() < t_end:
            worker.train(
                random_sparse(512, 1 << 12, 8, seed=i + j, w_true=w_true)
                for j in range(args.steps_per_tick)
            )
            i += args.steps_per_tick
            time.sleep(0.25)
    except KeyboardInterrupt:
        pass
    finally:
        close_cluster(srv)
        worker.executor.stop()
        po.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_demo_main())
