"""Logical-clock span tracing: host wall time correlated to executor time.

The executor's logical clocks (``Task.time``) order every step but carry
no timing; ``bench.py`` can summarize an XLA device trace but sees
nothing host-side. A *span* bridges the two: a host wall-time interval
stamped with the logical timestamp it serves, emitted as one JSONL line
through the process sink. The executor emits one ``executor.step`` event
per finished step carrying all three phases (queue-wait from submit to
dispatch, run, materialize) so a trace reader can reconstruct the
pipeline without joining records.

Sink contract: append-only JSONL, one event per line, thread-safe,
best-effort (a tracing failure must never take down the step it was
measuring). ``install_sink(None)`` (the default) makes ``emit`` a cheap
None check — the hot path pays nothing when tracing is off.

Flow correlation (the timeline layer, :mod:`telemetry.timeline`): a
*flow id* names one unit of work — a batch, a superbatch launch, a
served request — as it crosses threads (feeder → prep pool → uploader
→ trainer step; serve submit → coalescer flush → executor). The stage
that creates the unit allocates an id with :func:`new_flow`, each stage
runs its work under ``with flow_scope(fid):``, and every span emitted
inside the scope carries ``"flow": fid`` automatically, so a trace
reader can stitch the per-thread tracks back into per-unit paths
without the stages knowing about each other. The scope is a
thread-local; crossing a thread boundary means carrying the id in the
hand-off (a queue tuple, a ticket field) and re-entering the scope on
the far side.
"""

from __future__ import annotations

import contextlib
import io
import itertools
import json
import threading
import time
from typing import Any, Dict, Optional


class JsonlSink:
    """Append-only JSONL event sink (one dict per line)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f: Optional[io.TextIOWrapper] = open(path, "a", encoding="utf-8")

    def emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()  # readers (tests, tail -f) see events live

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


_sink_lock = threading.Lock()
_sink: Optional[JsonlSink] = None


def install_sink(sink: Optional[JsonlSink]) -> Optional[JsonlSink]:
    """Install the process event sink; returns the previous one (NOT
    closed — the caller owns both)."""
    global _sink
    with _sink_lock:
        prev, _sink = _sink, sink
        return prev


def get_sink() -> Optional[JsonlSink]:
    return _sink


def close_sink() -> None:
    """Close and uninstall the process sink (Postoffice.reset hook)."""
    global _sink
    with _sink_lock:
        sink, _sink = _sink, None
    if sink is not None:
        sink.close()


def emit(event: Dict[str, Any]) -> None:
    """Best-effort emit to the installed sink (no-op when none). Every
    event gains a ``thread`` field (the emitting thread's name) so the
    timeline reader can lay events out on per-thread tracks without the
    call sites threading identity through."""
    sink = _sink
    if sink is None:
        return
    with contextlib.suppress(Exception):
        if "thread" not in event:
            event["thread"] = threading.current_thread().name
        sink.emit(event)


# -- flow correlation ------------------------------------------------------

_flow_ids = itertools.count(1)  # count() is atomic under the GIL
_flow_local = threading.local()


def new_flow() -> int:
    """Allocate a fresh process-unique flow id (one per unit of work)."""
    return next(_flow_ids)


def maybe_new_flow() -> Optional[int]:
    """A fresh flow id when a sink is installed, else None — the
    producer-side idiom (only pay for flow ids when tracing is on;
    ``flow_scope(None)`` downstream is a no-op)."""
    return new_flow() if _sink is not None else None


@contextlib.contextmanager
def parked_sink():
    """Temporarily uninstall the span sink for a block — used around
    embedded A/B benches whose instrumented arms would otherwise pay a
    one-sided tracing tax and flood the run's trace with off-window
    events. Restores the previous sink on exit."""
    prev = install_sink(None)
    try:
        yield
    finally:
        install_sink(prev)


def current_flow() -> Optional[int]:
    """The flow id active on this thread, or None outside any scope."""
    return getattr(_flow_local, "flow", None)


@contextlib.contextmanager
def flow_scope(flow: Optional[int]):
    """Run a block with ``flow`` as this thread's active flow id; spans
    emitted inside carry it automatically. ``flow_scope(None)`` is a
    no-op passthrough (tracing off / no id carried), so hand-off code
    can use it unconditionally. Scopes nest; the previous id is
    restored on exit."""
    if flow is None:
        yield
        return
    prev = getattr(_flow_local, "flow", None)
    _flow_local.flow = flow
    try:
        yield
    finally:
        _flow_local.flow = prev


def abandoned(name: str, reason: str, flow: Optional[int] = None, **attrs) -> None:
    """Emit an explicit ``abandoned`` terminator for work that died
    before its span could close — the pool exception-forwarding path
    (utils/concurrent.OrderedStagePool) calls this so a worker
    exception leaves a tombstone in the timeline instead of an
    open-ended track."""
    event: Dict[str, Any] = {
        "kind": "span",
        "name": name,
        "t_wall": time.time(),
        "dur_s": 0.0,
        "abandoned": True,
        "reason": reason,
    }
    fid = flow if flow is not None else current_flow()
    if fid is not None:
        event["flow"] = fid
    event.update(attrs)
    emit(event)


@contextlib.contextmanager
def span(name: str, ts: Optional[int] = None, histogram=None, **attrs):
    """Time a host-side block and emit it as one JSONL event.

    ``ts`` is the executor logical timestamp the block serves — the
    correlation key between host spans and device steps. ``histogram``
    (a telemetry Histogram or labeled child) additionally records the
    duration, so the same interval feeds both the trace and the
    registry. Extra keyword attrs ride along verbatim.

    The thread's active :func:`flow_scope` id is attached as ``flow``
    (pass an explicit ``flow=`` attr to override). A block that exits
    via an exception still emits its event — with ``error`` naming the
    exception type — so the timeline never holds open-ended spans;
    MUST be used as a ``with`` statement (the pslint ``spans`` pass
    flags bare calls, whose block would otherwise never run).
    """
    t_wall = time.time()
    t0 = time.perf_counter()
    error: Optional[str] = None
    try:
        yield
    except BaseException as e:
        # only an exception that actually unwound THIS block is an
        # error of the span — sys.exc_info() in the finally would also
        # see an outer exception being handled around a clean block
        error = type(e).__name__
        raise
    finally:
        dur = time.perf_counter() - t0
        if histogram is not None:
            with contextlib.suppress(Exception):
                histogram.observe(dur)
        event = {"kind": "span", "name": name, "t_wall": t_wall, "dur_s": dur}
        if ts is not None:
            event["ts"] = ts
        fid = current_flow()
        if fid is not None:
            event["flow"] = fid
        if error is not None:
            event["error"] = error
        event.update(attrs)
        emit(event)
