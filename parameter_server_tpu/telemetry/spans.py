"""Logical-clock span tracing: host wall time correlated to executor time.

The executor's logical clocks (``Task.time``) order every step but carry
no timing; ``bench.py`` can summarize an XLA device trace but sees
nothing host-side. A *span* bridges the two: a host wall-time interval
stamped with the logical timestamp it serves, emitted as one JSONL line
through the process sink. The executor emits one ``executor.step`` event
per finished step carrying all three phases (queue-wait from submit to
dispatch, run, materialize) so a trace reader can reconstruct the
pipeline without joining records.

Sink contract: append-only JSONL, one event per line, thread-safe,
best-effort (a tracing failure must never take down the step it was
measuring). ``install_sink(None)`` (the default) makes ``emit`` a cheap
None check — the hot path pays nothing when tracing is off.

Flow correlation (the timeline layer, :mod:`telemetry.timeline`): a
*flow id* names one unit of work — a batch, a superbatch launch, a
served request — as it crosses threads (feeder → prep pool → uploader
→ trainer step; serve submit → coalescer flush → executor). The stage
that creates the unit allocates an id with :func:`new_flow`, each stage
runs its work under ``with flow_scope(fid):``, and every span emitted
inside the scope carries ``"flow": fid`` automatically, so a trace
reader can stitch the per-thread tracks back into per-unit paths
without the stages knowing about each other. The scope is a
thread-local; crossing a thread boundary means carrying the id in the
hand-off (a queue tuple, a ticket field) and re-entering the scope on
the far side.

Crossing a *process* boundary (the Van wire) means carrying the id in
the message header instead: :func:`trace_context` builds the
wire-safe ``{"flow", "node", "t_send"}`` dict ``Van.transfer`` stamps
onto ``Task.trace``, and :func:`activate_trace` re-enters the scope on
the receiving side. Flow ids are per-process counters, so the context
also names the ORIGIN node — spans emitted under a received flow carry
``flow_node`` and the multi-node timeline merge
(:func:`telemetry.timeline.merge_node_events`) namespaces flows by
``(origin node, id)`` so two nodes' local flow 7 never alias.
"""

from __future__ import annotations

import contextlib
import io
import itertools
import json
import threading
import time
from typing import Any, Dict, Optional


class JsonlSink:
    """Append-only JSONL event sink (one dict per line)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f: Optional[io.TextIOWrapper] = open(path, "a", encoding="utf-8")

    def emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()  # readers (tests, tail -f) see events live

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


_sink_lock = threading.Lock()
_sink: Optional[JsonlSink] = None
_parked_depth = 0  # guarded-by: _sink_lock — nested parked_sink() count


def install_sink(sink: Optional[JsonlSink]) -> Optional[JsonlSink]:
    """Install the process event sink; returns the previous one (NOT
    closed — the caller owns both)."""
    global _sink
    with _sink_lock:
        prev, _sink = _sink, sink
        return prev


def get_sink() -> Optional[JsonlSink]:
    return _sink


def sink_state() -> str:
    """One of ``active`` / ``parked`` / ``absent`` — so a reader of an
    empty timeline tail (/debug/snapshot) can tell "no trace captured
    because nothing is listening" apart from "nothing happened":
    ``parked`` means a sink exists but is temporarily uninstalled
    (:func:`parked_sink`, the embedded-A/B idiom), ``absent`` means no
    sink was ever installed (or it was closed)."""
    with _sink_lock:
        if _sink is not None:
            return "active"
        return "parked" if _parked_depth > 0 else "absent"


def close_sink() -> None:
    """Close and uninstall the process sink (Postoffice.reset hook)."""
    global _sink
    with _sink_lock:
        sink, _sink = _sink, None
    if sink is not None:
        sink.close()


def emit(event: Dict[str, Any]) -> None:
    """Best-effort emit to the installed sink (no-op when none). Every
    event gains a ``thread`` field (the emitting thread's name) so the
    timeline reader can lay events out on per-thread tracks without the
    call sites threading identity through."""
    sink = _sink
    if sink is None:
        return
    with contextlib.suppress(Exception):
        if "thread" not in event:
            event["thread"] = threading.current_thread().name
        sink.emit(event)


# -- flow correlation ------------------------------------------------------

_flow_ids = itertools.count(1)  # count() is atomic under the GIL
_flow_local = threading.local()


def new_flow() -> int:
    """Allocate a fresh process-unique flow id (one per unit of work)."""
    return next(_flow_ids)


def maybe_new_flow() -> Optional[int]:
    """A fresh flow id when a sink is installed, else None — the
    producer-side idiom (only pay for flow ids when tracing is on;
    ``flow_scope(None)`` downstream is a no-op)."""
    return new_flow() if _sink is not None else None


@contextlib.contextmanager
def parked_sink():
    """Temporarily uninstall the span sink for a block — used around
    embedded A/B benches whose instrumented arms would otherwise pay a
    one-sided tracing tax and flood the run's trace with off-window
    events. Restores the previous sink on exit. While parked,
    :func:`sink_state` reports ``parked`` (only if a sink actually
    existed — parking nothing is still ``absent``)."""
    global _parked_depth
    prev = install_sink(None)
    had_sink = prev is not None
    if had_sink:
        with _sink_lock:
            _parked_depth += 1
    try:
        yield
    finally:
        if had_sink:
            with _sink_lock:
                _parked_depth -= 1
        install_sink(prev)


def current_flow() -> Optional[int]:
    """The flow id active on this thread, or None outside any scope."""
    return getattr(_flow_local, "flow", None)


def current_flow_node() -> Optional[str]:
    """The ORIGIN node of the active flow, or None when the flow was
    allocated locally (the overwhelmingly common case)."""
    return getattr(_flow_local, "node", None)


@contextlib.contextmanager
def flow_scope(flow: Optional[int], node: Optional[str] = None):
    """Run a block with ``flow`` as this thread's active flow id; spans
    emitted inside carry it automatically. ``flow_scope(None)`` is a
    no-op passthrough (tracing off / no id carried), so hand-off code
    can use it unconditionally. Scopes nest; the previous id is
    restored on exit. ``node`` names the flow's ORIGIN process when the
    id was received off the wire (:func:`activate_trace`) — spans then
    carry ``flow_node`` so the cross-node merge can namespace the id."""
    if flow is None:
        yield
        return
    prev = getattr(_flow_local, "flow", None)
    prev_node = getattr(_flow_local, "node", None)
    _flow_local.flow = flow
    _flow_local.node = node
    try:
        yield
    finally:
        _flow_local.flow = prev
        _flow_local.node = prev_node


def node_id() -> str:
    """This PROCESS's identity on the trace plane — the same id the
    cluster metrics plane reports under (``PS_NODE_ID``, default H0)."""
    import os

    return os.environ.get("PS_NODE_ID", "H0")


def trace_context() -> Dict[str, Any]:
    """The wire trace context for an outgoing message — the
    restricted-unpickler-safe dict ``Van.transfer`` stamps onto
    ``Task.trace``: the sending thread's active flow id (when one is
    active), this process's node id, and the send wall time. ``t_send``
    and ``node`` are stamped even with tracing off: the receiver's
    clock-offset estimator (system/heartbeat.ClockSync) needs the send
    time on every report exchange, tracing or not — the cost is one
    small dict per control-plane frame."""
    ctx: Dict[str, Any] = {"node": current_flow_node() or node_id(),
                           "t_send": time.time()}
    fid = current_flow()
    if fid is not None:
        ctx["flow"] = int(fid)
    return ctx


def activate_trace(trace: Optional[Dict[str, Any]]):
    """Re-enter a received message's flow on THIS thread (the receiving
    executor) so the unit of work stays ONE flow across the Van:
    ``with activate_trace(msg.task.trace): handle(msg)``. A context
    without a flow (or None — legacy peer, tracing off) is a no-op
    passthrough. The origin node rides along as ``flow_node`` on every
    span emitted inside, unless the flow originated here."""
    if not isinstance(trace, dict):
        return contextlib.nullcontext()
    fid = trace.get("flow")
    if fid is None:
        return contextlib.nullcontext()
    origin = trace.get("node")
    if origin == node_id():
        origin = None  # local loopback: no namespacing needed
    return flow_scope(int(fid), node=origin)


def abandoned(name: str, reason: str, flow: Optional[int] = None, **attrs) -> None:
    """Emit an explicit ``abandoned`` terminator for work that died
    before its span could close — the pool exception-forwarding path
    (utils/concurrent.OrderedStagePool) calls this so a worker
    exception leaves a tombstone in the timeline instead of an
    open-ended track."""
    event: Dict[str, Any] = {
        "kind": "span",
        "name": name,
        "t_wall": time.time(),
        "dur_s": 0.0,
        "abandoned": True,
        "reason": reason,
    }
    fid = flow if flow is not None else current_flow()
    if fid is not None:
        event["flow"] = fid
    event.update(attrs)
    emit(event)


@contextlib.contextmanager
def span(name: str, ts: Optional[int] = None, histogram=None, **attrs):
    """Time a host-side block and emit it as one JSONL event.

    ``ts`` is the executor logical timestamp the block serves — the
    correlation key between host spans and device steps. ``histogram``
    (a telemetry Histogram or labeled child) additionally records the
    duration, so the same interval feeds both the trace and the
    registry. Extra keyword attrs ride along verbatim.

    The thread's active :func:`flow_scope` id is attached as ``flow``
    (pass an explicit ``flow=`` attr to override). A block that exits
    via an exception still emits its event — with ``error`` naming the
    exception type — so the timeline never holds open-ended spans;
    MUST be used as a ``with`` statement (the pslint ``spans`` pass
    flags bare calls, whose block would otherwise never run).
    """
    t_wall = time.time()
    t0 = time.perf_counter()
    error: Optional[str] = None
    try:
        yield
    except BaseException as e:
        # only an exception that actually unwound THIS block is an
        # error of the span — sys.exc_info() in the finally would also
        # see an outer exception being handled around a clean block
        error = type(e).__name__
        raise
    finally:
        dur = time.perf_counter() - t0
        if histogram is not None:
            with contextlib.suppress(Exception):
                histogram.observe(dur)
        event = {"kind": "span", "name": name, "t_wall": t_wall, "dur_s": dur}
        if ts is not None:
            event["ts"] = ts
        fid = current_flow()
        if fid is not None:
            event["flow"] = fid
            fnode = current_flow_node()
            if fnode is not None:
                event["flow_node"] = fnode
        if error is not None:
            event["error"] = error
        event.update(attrs)
        emit(event)
