"""Logical-clock span tracing: host wall time correlated to executor time.

The executor's logical clocks (``Task.time``) order every step but carry
no timing; ``bench.py`` can summarize an XLA device trace but sees
nothing host-side. A *span* bridges the two: a host wall-time interval
stamped with the logical timestamp it serves, emitted as one JSONL line
through the process sink. The executor emits one ``executor.step`` event
per finished step carrying all three phases (queue-wait from submit to
dispatch, run, materialize) so a trace reader can reconstruct the
pipeline without joining records.

Sink contract: append-only JSONL, one event per line, thread-safe,
best-effort (a tracing failure must never take down the step it was
measuring). ``install_sink(None)`` (the default) makes ``emit`` a cheap
None check — the hot path pays nothing when tracing is off.
"""

from __future__ import annotations

import contextlib
import io
import json
import threading
import time
from typing import Any, Dict, Optional


class JsonlSink:
    """Append-only JSONL event sink (one dict per line)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f: Optional[io.TextIOWrapper] = open(path, "a", encoding="utf-8")

    def emit(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()  # readers (tests, tail -f) see events live

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


_sink_lock = threading.Lock()
_sink: Optional[JsonlSink] = None


def install_sink(sink: Optional[JsonlSink]) -> Optional[JsonlSink]:
    """Install the process event sink; returns the previous one (NOT
    closed — the caller owns both)."""
    global _sink
    with _sink_lock:
        prev, _sink = _sink, sink
        return prev


def get_sink() -> Optional[JsonlSink]:
    return _sink


def close_sink() -> None:
    """Close and uninstall the process sink (Postoffice.reset hook)."""
    global _sink
    with _sink_lock:
        sink, _sink = _sink, None
    if sink is not None:
        sink.close()


def emit(event: Dict[str, Any]) -> None:
    """Best-effort emit to the installed sink (no-op when none)."""
    sink = _sink
    if sink is None:
        return
    with contextlib.suppress(Exception):
        sink.emit(event)


@contextlib.contextmanager
def span(name: str, ts: Optional[int] = None, histogram=None, **attrs):
    """Time a host-side block and emit it as one JSONL event.

    ``ts`` is the executor logical timestamp the block serves — the
    correlation key between host spans and device steps. ``histogram``
    (a telemetry Histogram or labeled child) additionally records the
    duration, so the same interval feeds both the trace and the
    registry. Extra keyword attrs ride along verbatim.
    """
    t_wall = time.time()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dur = time.perf_counter() - t0
        if histogram is not None:
            with contextlib.suppress(Exception):
                histogram.observe(dur)
        event = {"kind": "span", "name": name, "t_wall": t_wall, "dur_s": dur}
        if ts is not None:
            event["ts"] = ts
        event.update(attrs)
        emit(event)
