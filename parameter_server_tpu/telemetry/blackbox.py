"""Black-box flight recorder + alert-triggered diagnostic bundles.

Every observability plane built so far is either *streaming* (the JSONL
span sink — gone if nobody installed it) or *cumulative* (the metrics
registry — totals, no recent history). When an SLO alert fires or a
shard wedges, the question is always "what happened in the last few
seconds", and by the time a human attaches, that evidence is gone.
This module keeps it: a bounded in-memory ring of recent span events
plus periodic metrics-delta samples per node (the aircraft flight
recorder, :class:`FlightRecorder` — zero file IO, overhead measured
below the host noise floor by the in-record paired A/B), and a
*trigger plane* that snapshots everything into one self-contained
**diagnostic bundle** at the moment of an incident:

- alert ``pending→firing`` transitions (``AuxRuntime.set_alerts``),
- ``DegradedError`` raises on the serving path,
- a node declared dead by the RecoveryCoordinator (the drill's shard
  kill — the record attaches the bundle under ``blackbox``),
- a wedged executor ``wait`` timeout.

A bundle carries ring dumps from every node — fetched over the Van
message plane with staleness semantics for silent nodes
(``AuxRuntime.fetch_rings``) — the aggregated metrics snapshot, alert
states, executor pending/timestamps, the device-truth section, per-peer
clock offsets, the down-sampled **history hour** before the trigger
(telemetry/history.py — the installed ring exported at the coarsest
resolution covering 3600 s), and a Perfetto-ready ``trace`` (open
``bundle["trace"]`` at https://ui.perfetto.dev). It is served live at ``/debug/bundle``
(telemetry/exposition.py) and on demand via ``make bundle``.

Threading: the recorder is **lock-annotated** shared state (spans are
emitted from every pipeline thread — the stateless-or-feeder rule's
"or lock-annotated" arm); captures are rate-limited
(:func:`set_min_interval`) so a trigger storm costs one bundle, not
one per symptom, and :func:`trigger_bundle` never raises — diagnosis
must not take down the path it is diagnosing.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, List, Optional

from . import registry as telemetry_registry
from . import spans as _spans

_LOG = logging.getLogger(__name__)

#: default ring capacity (span events per node); ~200 bytes/event in
#: practice, so the default ring tops out around half a megabyte
DEFAULT_CAPACITY = 2048
#: default metrics-delta sample capacity per node
DEFAULT_METRICS_CAPACITY = 64
#: default minimum seconds between auto-captured bundles
DEFAULT_MIN_INTERVAL_S = 30.0


def _tel():
    from .instruments import cached_blackbox_instruments

    return cached_blackbox_instruments()


def _bundle_tel():
    from .instruments import cached_bundle_instruments

    return cached_bundle_instruments()


class FlightRecorder:
    """Bounded in-memory ring of recent span events + metrics deltas.

    Appends come from every span-emitting thread (via :class:`TeeSink`)
    — one lock acquire + one deque append, no file IO ever. Eviction is
    the deque's ``maxlen``; :meth:`dump` snapshots under the lock so a
    capture never reads a torn ring.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        metrics_capacity: int = DEFAULT_METRICS_CAPACITY,
        node_id: Optional[str] = None,
    ):
        self.node_id = node_id or _spans.node_id()
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(  # guarded-by: _lock
            maxlen=self.capacity
        )
        self._events_total = 0  # guarded-by: _lock
        self._metrics: collections.deque = collections.deque(  # guarded-by: _lock
            maxlen=int(metrics_capacity)
        )
        self._metrics_total = 0  # guarded-by: _lock
        self._last_flat: Optional[Dict[str, float]] = None  # guarded-by: _lock
        self._published_events = 0  # guarded-by: _lock
        self._published_samples = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    # -- the hot path (TeeSink.emit) --

    def emit(self, event: Dict[str, Any]) -> None:
        """Absorb one span event (thread-safe; the steady-state cost
        the in-record A/B prices)."""
        with self._lock:
            self._ring.append(event)
            self._events_total += 1

    # -- metrics-delta sampling (periodic, NOT per event) --

    @staticmethod
    def _flatten(export: Dict[str, dict]) -> Dict[str, float]:
        """Registry export → flat ``name{labels}`` → cumulative value
        (counter values; histogram counts — the delta-able scalars)."""
        flat: Dict[str, float] = {}
        for name, decl in export.items():
            kind = decl.get("type")
            for s in decl.get("series", ()):
                labels = ",".join(
                    f"{k}={v}" for k, v in sorted(s.get("labels", {}).items())
                )
                key = f"{name}{{{labels}}}" if labels else name
                if kind == "counter":
                    flat[key] = float(s["value"])
                elif kind == "histogram":
                    flat[key + "_count"] = float(s["count"])
        return flat

    def sample_metrics(
        self,
        export: Optional[Dict[str, dict]] = None,
        reg=None,
        t: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Record one metrics-delta sample: counter/histogram-count
        increases since the previous sample (gauge churn is point-in-
        time noise the deltas would misrepresent; gauges live in the
        bundle's full metrics snapshot instead). Driven periodically —
        a report-timer cadence, never per event."""
        if export is None:
            export = (reg or telemetry_registry.default_registry()).export_state()
        flat = self._flatten(export)
        t = time.time() if t is None else t
        with self._lock:
            prev = self._last_flat or {}
            delta = {
                k: round(v - prev.get(k, 0.0), 6)
                for k, v in flat.items()
                if v > prev.get(k, 0.0)
            }
            self._last_flat = flat
            sample = {"t_wall": t, "delta": delta}
            self._metrics.append(sample)
            self._metrics_total += 1
        self._publish()
        return sample

    def _publish(self) -> None:
        """Push ring totals into the registry (ps_blackbox_*) — called
        from the periodic/sample/dump paths so the hot emit path never
        touches registry locks (the catalog documents the lazy
        cadence)."""
        tel = _tel()
        if tel is None:
            return
        with self._lock:
            ev_delta = self._events_total - self._published_events
            sm_delta = self._metrics_total - self._published_samples
            self._published_events = self._events_total
            self._published_samples = self._metrics_total
            ring_len = len(self._ring)
        if ev_delta > 0:
            tel["events"].inc(ev_delta)
        if sm_delta > 0:
            tel["samples"].inc(sm_delta)
        tel["ring_events"].set(ring_len)

    # -- reads --

    def dump(self) -> Dict[str, Any]:
        """A consistent snapshot of the ring — the per-node payload of
        a diagnostic bundle (plain dicts/lists/scalars, so it survives
        the restricted wire unpickler)."""
        with self._lock:
            events = list(self._ring)
            samples = list(self._metrics)
            total = self._events_total
        self._publish()
        return {
            "node": self.node_id,
            "t_dump": time.time(),
            "capacity": self.capacity,
            "events_total": total,
            "dropped": max(0, total - len(events)),
            "events": events,
            "metrics_samples": samples,
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._metrics.clear()
            self._last_flat = None


class TeeSink:
    """Span-sink tee: every event lands in the flight recorder AND the
    wrapped inner sink (when one exists). Installing the tee with no
    inner sink is the always-on black-box mode: spans are recorded,
    nothing is written to disk. ``path`` proxies the inner sink's so
    timeline readers (/debug/snapshot's tail) keep working."""

    def __init__(self, recorder: FlightRecorder, inner=None):
        self.recorder = recorder
        self.inner = inner

    @property
    def path(self) -> Optional[str]:
        return getattr(self.inner, "path", None)

    def emit(self, event: Dict[str, Any]) -> None:
        self.recorder.emit(event)
        if self.inner is not None:
            self.inner.emit(event)

    def close(self) -> None:
        if self.inner is not None:
            self.inner.close()


# -- process registry of recorders ----------------------------------------

_reg_lock = threading.Lock()
_recorders: Dict[str, FlightRecorder] = {}  # guarded by _reg_lock


def recorder(
    node_id: Optional[str] = None, create: bool = True
) -> Optional[FlightRecorder]:
    """The named node's recorder (default: this process's node id),
    created on first use unless ``create=False``."""
    nid = node_id or _spans.node_id()
    with _reg_lock:
        rec = _recorders.get(nid)
        if rec is None and create:
            rec = _recorders[nid] = FlightRecorder(node_id=nid)
        return rec


def recorders() -> Dict[str, FlightRecorder]:
    with _reg_lock:
        return dict(_recorders)


def drop_recorder(node_id: str) -> None:
    """Remove one node's recorder (a drill or test cleaning up its OWN
    per-node recorders without resetting the process trigger plane)."""
    with _reg_lock:
        _recorders.pop(node_id, None)


def installed_recorder() -> Optional[FlightRecorder]:
    """The recorder behind the installed span sink (when the sink is a
    :class:`TeeSink`), else None."""
    sink = _spans.get_sink()
    return sink.recorder if isinstance(sink, TeeSink) else None


def arm(
    rec: Optional[FlightRecorder] = None, node_id: Optional[str] = None
) -> FlightRecorder:
    """Install the flight recorder as a tee over the current span sink
    (idempotent: re-arming the same recorder is a no-op). Armed with no
    inner sink, the black box records with zero file IO."""
    rec = rec or recorder(node_id)
    cur = _spans.get_sink()
    if isinstance(cur, TeeSink) and cur.recorder is rec:
        return rec
    _spans.install_sink(TeeSink(rec, inner=cur))
    return rec


def disarm() -> None:
    """Remove the tee, restoring the inner sink (no-op when not armed)."""
    cur = _spans.get_sink()
    if isinstance(cur, TeeSink):
        _spans.install_sink(cur.inner)


def reset() -> None:
    """Test hermeticity: disarm, drop every recorder, clear bundles and
    the trigger rate limiter."""
    global _last_trigger_t, _min_interval_s
    disarm()
    with _reg_lock:
        _recorders.clear()
    with _trigger_lock:
        _bundles.clear()
        _last_trigger_t = None
        _min_interval_s = DEFAULT_MIN_INTERVAL_S


# -- diagnostic bundles ----------------------------------------------------


def _guarded(section_fn, errors: Dict[str, str], name: str):
    """One bundle section, captured best-effort: a broken source
    records its error string instead of killing the whole capture."""
    try:
        return section_fn()
    except Exception as e:  # noqa: BLE001 — diagnosis must degrade,
        # not fail: a bundle with one missing section beats no bundle
        errors[name] = f"{type(e).__name__}: {str(e)[:200]}"
        return None


def capture_bundle(
    trigger: str = "manual",
    detail: str = "",
    aux=None,
    rings: Optional[Dict[str, dict]] = None,
    stale: Optional[Dict[str, str]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Capture one self-contained diagnostic bundle right now.

    ``aux`` (an AuxRuntime) supplies the cluster context: ring dumps
    fetched from every node over the Van (``fetch_rings`` — staleness
    for silent nodes), the node-labeled metrics snapshot, alert states
    and clock offsets. Without it, the capture is process-local (the
    armed recorder + the default registry). ``rings`` overrides the
    ring source entirely; ``stale`` marks named nodes stale (a caller
    — the recovery coordinator — knows who just died even when the
    aggregator has not noticed yet). Every section is best-effort; a
    broken source records its error under ``section_errors``.
    """
    t0 = time.perf_counter()
    errors: Dict[str, str] = {}
    if rings is None:
        if aux is not None:
            rings = _guarded(lambda: aux.fetch_rings(), errors, "rings") or {}
        else:
            rings = {
                nid: rec.dump() for nid, rec in sorted(recorders().items())
            }
            inst = installed_recorder()
            if inst is not None and inst.node_id not in rings:
                rings[inst.node_id] = inst.dump()
    rings = dict(rings)
    for nid, reason in (stale or {}).items():
        rings[nid] = {"stale": True, "reason": reason}

    def _metrics():
        if aux is not None:
            return aux.cluster.snapshot()
        return telemetry_registry.default_registry().snapshot()

    def _alerts():
        mgr = getattr(aux, "alerts", None) if aux is not None else None
        return mgr.snapshot() if mgr is not None else None

    def _executors():
        from ..system.executor import live_executors

        return sorted(
            (ex.debug_state() for ex in live_executors()),
            key=lambda d: d["name"],
        )

    def _device():
        from . import device as device_mod

        return device_mod.snapshot()

    def _clock():
        return aux.clock.snapshot() if aux is not None else {}

    def _history():
        # the down-sampled hour before the trigger: the installed
        # process history ring (telemetry/history.py), folded once so
        # the open second lands in the capture. installed_store never
        # creates — a process without a history plane bundles None,
        # which is a disclosed absence, not an empty ring.
        from . import history as history_mod

        store = history_mod.installed_store()
        if store is None:
            return None
        store.fold(force=True)
        return store.export_ring(window_s=3600.0)

    def _trace():
        from . import timeline as timeline_mod

        events_by_node = {
            nid: d["events"]
            for nid, d in rings.items()
            if isinstance(d, dict) and d.get("events")
        }
        offsets = aux.clock.offsets() if aux is not None else {}
        merged = timeline_mod.merge_node_events(events_by_node, offsets)
        return timeline_mod.to_chrome_trace(merged)

    bundle: Dict[str, Any] = {
        "kind": "ps_diagnostic_bundle",
        "version": 1,
        "trigger": {"kind": trigger, "detail": detail, "t_wall": time.time()},
        "node_id": _spans.node_id(),
        "rings": rings,
        "metrics": _guarded(_metrics, errors, "metrics"),
        "alerts": _guarded(_alerts, errors, "alerts"),
        "executors": _guarded(_executors, errors, "executors"),
        "device": _guarded(_device, errors, "device"),
        "clock_offsets": _guarded(_clock, errors, "clock_offsets"),
        "history": _guarded(_history, errors, "history"),
        "trace": _guarded(_trace, errors, "trace"),
    }
    if extra:
        bundle["extra"] = extra
    if errors:
        bundle["section_errors"] = errors
    tel = _bundle_tel()
    if tel is not None:
        tel["captures"].labels(trigger=trigger).inc()
        tel["capture_seconds"].observe(time.perf_counter() - t0)
        tel["last_ring_nodes"].set(len(rings))
    return bundle


def summarize_bundle(bundle: Dict[str, Any]) -> Dict[str, Any]:
    """A record-embeddable digest of a bundle (the drill's ``blackbox``
    section): per-node ring event counts / staleness, alert states,
    trigger — everything an assertion needs without megabytes of
    events in a bench record."""
    rings = bundle.get("rings", {})
    nodes = {}
    for nid, d in sorted(rings.items()):
        if not isinstance(d, dict):
            continue
        if d.get("stale") or d.get("absent"):
            nodes[nid] = {
                "stale": bool(d.get("stale")),
                "absent": bool(d.get("absent")),
                "reason": d.get("reason", ""),
            }
        else:
            nodes[nid] = {
                "stale": False,
                "events": len(d.get("events", ())),
                "events_total": d.get("events_total", 0),
                "metrics_samples": len(d.get("metrics_samples", ())),
            }
    alerts = bundle.get("alerts") or {}
    states = {
        name: st.get("state_name")
        for name, st in (alerts.get("states") or {}).items()
    }
    hist = bundle.get("history") or {}
    return {
        "captured": True,
        "trigger": dict(bundle.get("trigger", {})),
        "nodes": nodes,
        "alert_states": states,
        "trace_events": len((bundle.get("trace") or {}).get(
            "traceEvents", ())),
        "history_series": int(hist.get("series", 0)),
        "history_window_s": hist.get("window_s"),
        "section_errors": bundle.get("section_errors", {}),
    }


# -- the trigger plane -----------------------------------------------------

_trigger_lock = threading.Lock()
# monotonic time of the last capture, or None before any — a None
# sentinel, NOT 0.0: monotonic() can legitimately be smaller than the
# rate-limit interval on a freshly booted host, which would suppress
# the very first capture
_last_trigger_t: Optional[float] = None  # guarded by _trigger_lock
_min_interval_s = DEFAULT_MIN_INTERVAL_S  # guarded by _trigger_lock
_bundles: collections.deque = collections.deque(maxlen=4)  # guarded by _trigger_lock


def set_min_interval(seconds: float) -> float:
    """Set the auto-capture rate limit; returns the previous value
    (tests and drills drop it to 0 to capture deterministically)."""
    global _min_interval_s
    with _trigger_lock:
        prev, _min_interval_s = _min_interval_s, float(seconds)
        return prev


def trigger_bundle(
    trigger: str,
    detail: str = "",
    aux=None,
    stale: Optional[Dict[str, str]] = None,
) -> Optional[Dict[str, Any]]:
    """Auto-capture entry point for the trigger plane (alert firing,
    DegradedError, node death, wedged wait). Rate-limited — a trigger
    storm captures once per interval, the rest count as suppressed —
    and NEVER raises: the capture is a side effect of a failure path
    that must stay on its original course. Returns the bundle, or None
    when suppressed/failed."""
    global _last_trigger_t
    try:
        with _trigger_lock:
            now = time.monotonic()
            if (
                _last_trigger_t is not None
                and now - _last_trigger_t < _min_interval_s
            ):
                tel = _bundle_tel()
                if tel is not None:
                    tel["suppressed"].inc()
                return None
            _last_trigger_t = now
        bundle = capture_bundle(
            trigger=trigger, detail=detail, aux=aux, stale=stale
        )
        with _trigger_lock:
            _bundles.append(bundle)
        return bundle
    except Exception:  # noqa: BLE001 — see docstring
        _LOG.exception("diagnostic bundle capture failed (%s)", trigger)
        return None


def last_bundle() -> Optional[Dict[str, Any]]:
    with _trigger_lock:
        return _bundles[-1] if _bundles else None


def bundles() -> List[Dict[str, Any]]:
    with _trigger_lock:
        return list(_bundles)


# -- in-record overhead A/B (the PR 9 disarmed-overhead pattern) -----------


def overhead_ab(reps: int = 5, n: int = 400) -> Dict[str, Any]:
    """Steady-state recorder overhead, measured the PR 9 disarmed-
    overhead way: the SAME span-instrumented work stream (spans wrap
    real work, as they do in production — span density per unit work is
    what matters, not a bare span loop) with the ring armed (tee, no
    inner sink — the always-on black-box mode) vs no sink at all, both
    orders inside one rep so a monotone capacity drift on this flapping
    host cancels out of the paired ratio. The honest claim is the
    median ratio straddling the host's noise floor; because the stream
    ratio is hostage to seconds-scale capacity flaps, the absolute cost
    is ALSO priced as a tight-loop ``armed_ns_per_event`` a flap cannot
    fake. Zero file IO in both arms — asserted by the tee having no
    path."""
    rec = FlightRecorder(capacity=1024, node_id="ovh")
    tee = TeeSink(rec, inner=None)
    assert tee.path is None  # armed-but-idle: no file IO by construction
    sink_of = {"armed": tee, "off": None}

    def stream() -> float:
        # ~50-100µs of real work per span — the production span density
        # (a span wraps a prep stage or an executor step, never nothing)
        acc = 0.0
        for i in range(n):
            with _spans.span("bb.ovh"):
                for j in range(1500):
                    acc += j * 1e-9
        return acc

    def timed(arm: str) -> float:
        _spans.install_sink(sink_of[arm])
        t0 = time.perf_counter()
        stream()
        return time.perf_counter() - t0

    prev = _spans.install_sink(None)
    try:
        timed("armed")  # warm both shapes
        timed("off")
        ratios = []
        for _ in range(reps):
            # both orders inside one rep: armed, off, off, armed
            a1 = timed("armed")
            o = (timed("off") + timed("off")) / 2
            a2 = timed("armed")
            ratios.append(((a1 + a2) / 2) / max(o, 1e-9))
        # tight-loop absolute: empty spans, armed — the pure per-event
        # recorder cost (dict build + tee emit + ring append)
        _spans.install_sink(tee)
        m = 20_000
        t0 = time.perf_counter()
        for _ in range(m):
            with _spans.span("bb.tight"):
                pass
        armed_ns = (time.perf_counter() - t0) / m * 1e9
        _spans.install_sink(None)
        t0 = time.perf_counter()
        for _ in range(m):
            with _spans.span("bb.tight"):
                pass
        off_ns = (time.perf_counter() - t0) / m * 1e9
    finally:
        _spans.install_sink(prev)
    ratios.sort()
    return {
        "reps": reps,
        "spans_per_rep": n,
        "ratio_median": round(ratios[len(ratios) // 2], 3),
        "armed_ns_per_event": round(armed_ns, 1),
        "disarmed_ns_per_event": round(off_ns, 1),
        "added_ns_per_event": round(armed_ns - off_ns, 1),
        "file_io": False,
    }
