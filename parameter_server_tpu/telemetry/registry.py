"""Process-wide metrics registry: Counter / Gauge / Histogram.

The reference scatters its counters across layers (Van send_bytes_/
recv_bytes_, HeartbeatInfo traffic, Dashboard columns, MonitorMaster
progress merging). This module is the single spine those feed: named
instruments registered once per process, each guarded by its own lock
(the record path is one lock acquire + O(1) arithmetic; histograms add
a bisect over a fixed bucket list), snapshotted as JSON-friendly dicts
and rendered as Prometheus text exposition so humans and scrapers read
the same numbers.

Registration semantics: registering a *name* twice is an error
(``DuplicateMetricError``) — two call sites silently sharing (or
shadowing) a series is how counters go wrong. Instrumentation that runs
per-instance (every Executor, every parameter store) goes through the
``ensure_*`` accessors, which return the existing instrument when the
declaration matches exactly and raise when it does not — idempotent
without masking a genuine collision.

The default registry is process-global and hangs off ``Postoffice``
(``Postoffice.instance().metrics``); ``Postoffice.reset()`` swaps in a
fresh one so tests stay hermetic.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# default latency buckets: 10us .. ~100s, x~3.2 per step — wide enough
# for both a CPU-mesh unit test and a tunneled-TPU step
DEFAULT_BUCKETS = (
    1e-5, 3.2e-5, 1e-4, 3.2e-4, 1e-3, 3.2e-3, 1e-2, 3.2e-2,
    1e-1, 3.2e-1, 1.0, 3.2, 10.0, 32.0, 100.0,
)


class DuplicateMetricError(ValueError):
    """A metric name was registered twice (or re-declared differently)."""


def _validate_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(
            f"metric name {name!r} is not snake_case "
            "([a-z][a-z0-9_]*; no dots, dashes or capitals)"
        )
    return name


def _label_key(
    labelnames: Tuple[str, ...], labels: Dict[str, str]
) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared {labelnames}"
        )
    return tuple(str(labels[n]) for n in labelnames)


def _escape(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _help_line(name: str, help: str) -> str:
    """One ``# HELP`` line, escaped per the exposition spec (backslash
    and newline only — HELP text is not quoted, so no quote escaping)."""
    return (
        f"# HELP {name} "
        + help.replace("\\", "\\\\").replace("\n", "\\n")
    )


def _histogram_lines(
    name: str, label_fmt, bounds, bucket_counts, count: int, total: float
) -> List[str]:
    """The Prometheus histogram text series (cumulative ``_bucket``
    lines, ``+Inf``, ``_sum``, ``_count``) — the ONE renderer shared by
    the live registry and the cluster aggregator
    (telemetry/aggregate.py), so the text format cannot drift between
    the two /metrics producers. ``label_fmt(extra)`` renders the series'
    label block with ``extra`` (the ``le`` pair) appended."""
    lines: List[str] = []
    cum = 0
    for bound, c in zip(bounds, bucket_counts):
        cum += c
        le = 'le="%s"' % _fmt(bound)
        lines.append(f"{name}_bucket{label_fmt(le)} {cum}")
    inf = 'le="+Inf"'
    lines.append(f"{name}_bucket{label_fmt(inf)} {count}")
    lines.append(f"{name}_sum{label_fmt('')} {_fmt(total)}")
    lines.append(f"{name}_count{label_fmt('')} {count}")
    return lines


class Instrument:
    """Base: name/help/labelnames + the per-instrument lock."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        self.name = _validate_name(name)
        self.help = help
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            _validate_name(ln)
        self._lock = threading.Lock()

    # -- declaration identity (ensure_* matching) --

    def _decl(self) -> tuple:
        return (self.kind, self.name, self.labelnames)

    def _series_lines(self) -> List[str]:
        raise NotImplementedError

    def _snapshot_values(self):
        raise NotImplementedError

    def _export_series(self) -> List[dict]:
        """Raw, JSON-able series state (telemetry/aggregate.py): unlike
        ``_snapshot_values`` this keeps histogram BUCKET COUNTS rather
        than derived percentiles, so exports from different nodes can be
        merged bucket-wise without losing information."""
        raise NotImplementedError

    def _export_decl(self) -> dict:
        out = {
            "type": self.kind,
            "help": self.help,
            "labelnames": list(self.labelnames),
            "series": self._export_series(),
        }
        return out

    def _label_str(self, key: Tuple[str, ...]) -> str:
        if not self.labelnames:
            return ""
        return ",".join(f"{n}={v}" for n, v in zip(self.labelnames, key))

    def _prom_labels(self, key: Tuple[str, ...], extra: str = "") -> str:
        parts = [
            f'{n}="{_escape(v)}"' for n, v in zip(self.labelnames, key)
        ]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""


class _ScalarChild:
    """One labeled series of a Counter/Gauge — the O(1) hot-path handle."""

    __slots__ = ("_parent", "_key")

    def __init__(self, parent: "Instrument", key: Tuple[str, ...]):
        self._parent = parent
        self._key = key

    def inc(self, n: float = 1.0) -> None:
        self._parent._inc(self._key, n)

    def set(self, v: float) -> None:
        self._parent._set(self._key, v)

    def dec(self, n: float = 1.0) -> None:
        self._parent._inc(self._key, -n)

    @property
    def value(self) -> float:
        return self._parent.value(
            **dict(zip(self._parent.labelnames, self._key))
        )


class Counter(Instrument):
    """Monotone counter. ``inc`` only; negative increments are an error."""

    kind = "counter"

    def __init__(self, name, help="", labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def labels(self, **labels: str) -> _ScalarChild:
        return _ScalarChild(self, _label_key(self.labelnames, labels))

    def inc(self, n: float = 1.0) -> None:
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels(); use .labels()")
        self._inc((), n)

    def _inc(self, key: Tuple[str, ...], n: float) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels: str) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def _series_lines(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            f"{self.name}{self._prom_labels(k)} {_fmt(v)}" for k, v in items
        ]

    def _snapshot_values(self):
        with self._lock:
            return {self._label_str(k): v for k, v in sorted(self._values.items())}

    def _export_series(self) -> List[dict]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            {"labels": dict(zip(self.labelnames, k)), "value": v}
            for k, v in items
        ]


class Gauge(Counter):
    """Point-in-time value: ``set``/``inc``/``dec``."""

    kind = "gauge"

    def set(self, v: float) -> None:
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels(); use .labels()")
        self._set((), v)

    def dec(self, n: float = 1.0) -> None:
        self._inc((), -n)

    def _inc(self, key: Tuple[str, ...], n: float) -> None:  # signed ok
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def _set(self, key: Tuple[str, ...], v: float) -> None:
        with self._lock:
            self._values[key] = float(v)


class _HistogramSeries:
    __slots__ = ("bucket_counts", "count", "sum", "min", "max")

    def __init__(self, nbuckets: int):
        self.bucket_counts = [0] * nbuckets  # per finite upper bound
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf


class _HistogramChild:
    __slots__ = ("_parent", "_key")

    def __init__(self, parent: "Histogram", key: Tuple[str, ...]):
        self._parent = parent
        self._key = key

    def observe(self, v: float) -> None:
        self._parent._observe(self._key, v)


class Histogram(Instrument):
    """Cumulative histogram over fixed buckets (Prometheus ``le`` style).

    ``percentile(q)`` interpolates linearly inside the bucket holding the
    rank — exact when observations sit on bucket bounds, within one
    bucket's width otherwise.
    """

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(float(b) for b in (buckets or DEFAULT_BUCKETS)))
        if not bs or any(b != b or b == math.inf for b in bs):
            raise ValueError(f"bad buckets for {name}: {buckets}")
        self.buckets = bs  # finite upper bounds; +Inf is implicit
        self._series: Dict[Tuple[str, ...], _HistogramSeries] = {}

    def _decl(self) -> tuple:
        return (self.kind, self.name, self.labelnames, self.buckets)

    def labels(self, **labels: str) -> _HistogramChild:
        return _HistogramChild(self, _label_key(self.labelnames, labels))

    def observe(self, v: float) -> None:
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels(); use .labels()")
        self._observe((), v)

    def _observe(self, key: Tuple[str, ...], v: float) -> None:
        v = float(v)
        # first bucket whose upper bound is >= v (cumulative `le` style)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistogramSeries(len(self.buckets))
            if idx < len(self.buckets):
                s.bucket_counts[idx] += 1
            s.count += 1
            s.sum += v
            if v < s.min:
                s.min = v
            if v > s.max:
                s.max = v

    # -- reads --

    def count(self, **labels: str) -> int:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            s = self._series.get(key)
            return s.count if s else 0

    def sum(self, **labels: str) -> float:
        key = _label_key(self.labelnames, labels)
        with self._lock:
            s = self._series.get(key)
            return s.sum if s else 0.0

    def percentile(self, q: float, **labels: str) -> float:
        """q in [0, 1]. Linear interpolation inside the owning bucket;
        observations above the last bound clamp to the observed max."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"q={q} outside [0, 1]")
        key = _label_key(self.labelnames, labels)
        with self._lock:
            return self._percentile_locked(self._series.get(key), q)

    def _percentile_locked(self, s: Optional[_HistogramSeries], q: float) -> float:
        if s is None or s.count == 0:
            return math.nan
        rank = q * s.count
        cum = 0.0
        for i, c in enumerate(s.bucket_counts):
            if c == 0:
                continue
            # bucket 0 has no finite lower bound; the observed min is
            # the tightest honest edge
            lo = self.buckets[i - 1] if i else min(s.min, self.buckets[0])
            if cum + c >= rank:
                frac = (rank - cum) / c
                hi = self.buckets[i]
                return lo + frac * (hi - lo)
            cum += c
        return s.max  # rank lives above the last finite bound

    def _series_lines(self) -> List[str]:
        lines: List[str] = []
        with self._lock:
            items = [
                (key, list(s.bucket_counts), s.count, s.sum)
                for key, s in sorted(self._series.items())
            ]
        for key, counts, count, total in items:
            lines.extend(_histogram_lines(
                self.name,
                lambda extra, key=key: self._prom_labels(key, extra),
                self.buckets, counts, count, total,
            ))
        return lines

    def _snapshot_values(self):
        # percentiles computed from the series objects directly — the
        # formatted label string is display-only and cannot be parsed
        # back (label values may contain commas or '=')
        out = {}
        with self._lock:
            for key, s in sorted(self._series.items()):
                out[self._label_str(key)] = {
                    "count": s.count,
                    "sum": s.sum,
                    "avg": s.sum / s.count if s.count else None,
                    "min": None if s.count == 0 else s.min,
                    "max": None if s.count == 0 else s.max,
                    "p50": self._percentile_locked(s, 0.5),
                    "p90": self._percentile_locked(s, 0.9),
                    "p99": self._percentile_locked(s, 0.99),
                }
        return out

    def _export_series(self) -> List[dict]:
        out = []
        with self._lock:
            for key, s in sorted(self._series.items()):
                out.append({
                    "labels": dict(zip(self.labelnames, key)),
                    "buckets": list(s.bucket_counts),
                    "count": s.count,
                    "sum": s.sum,
                    "min": None if s.count == 0 else s.min,
                    "max": None if s.count == 0 else s.max,
                })
        return out

    def _export_decl(self) -> dict:
        out = super()._export_decl()
        out["buckets"] = list(self.buckets)
        return out


class MetricsRegistry:
    """Name → instrument, with strict and idempotent registration.

    Hot-path producers that cannot afford per-event instrument locks
    (the executor dispatch loop) buffer locally and register a
    *collector* — a zero-arg callable invoked before every
    ``snapshot()``/``render_text()`` so reads always see flushed data.
    Collectors are held by weak reference: a producer that dies simply
    stops being collected.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, Instrument] = {}
        self._collectors: List[object] = []  # weakref.ref / WeakMethod
        #: wall seconds the last collect() pass took — the history
        #: plane publishes it as ``ps_registry_collect_seconds`` (meta-
        #: monitoring: who watches the watcher). Single float, atomic
        #: in CPython; None until the first pass runs.
        self.last_collect_s: Optional[float] = None

    def add_collector(self, fn) -> None:
        """Register a flush hook (bound methods are weakly referenced)."""
        import weakref

        ref = (
            weakref.WeakMethod(fn)
            if hasattr(fn, "__self__")
            else weakref.ref(fn)
        )
        with self._lock:
            self._collectors.append(ref)

    def collect(self) -> None:
        """Run every live collector; prune the dead ones."""
        import time as _time

        t0 = _time.perf_counter()
        with self._lock:
            refs = list(self._collectors)
        dead = []
        for ref in refs:
            fn = ref()
            if fn is None:
                dead.append(ref)
                continue
            try:
                fn()
            except Exception:
                pass  # a broken producer must not poison the snapshot
        self.last_collect_s = _time.perf_counter() - t0
        if dead:
            with self._lock:
                self._collectors = [
                    r for r in self._collectors if r not in dead
                ]

    # -- strict registration: duplicate name is an error --

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._register(Counter(name, help, labelnames))

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._register(Gauge(name, help, labelnames))

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        return self._register(Histogram(name, help, labelnames, buckets))

    def _register(self, inst: Instrument) -> Instrument:
        with self._lock:
            if inst.name in self._instruments:
                raise DuplicateMetricError(
                    f"metric {inst.name!r} already registered"
                )
            # histogram suffixes collide with scalar series of the same
            # base name in the exposition — reserve them
            for other in self._instruments.values():
                if isinstance(other, Histogram) or isinstance(inst, Histogram):
                    h, o = (inst, other) if isinstance(inst, Histogram) else (other, inst)
                    if o.name in (f"{h.name}_bucket", f"{h.name}_sum", f"{h.name}_count"):
                        raise DuplicateMetricError(
                            f"metric {o.name!r} collides with histogram "
                            f"{h.name!r} exposition series"
                        )
            self._instruments[inst.name] = inst
            return inst

    # -- idempotent accessors for per-instance instrumentation --

    def _ensure(self, inst: Instrument) -> Instrument:
        with self._lock:
            existing = self._instruments.get(inst.name)
            if existing is not None:
                if existing._decl() != inst._decl():
                    raise DuplicateMetricError(
                        f"metric {inst.name!r} re-declared differently: "
                        f"{existing._decl()} vs {inst._decl()}"
                    )
                return existing
            self._instruments[inst.name] = inst
            return inst

    def ensure_counter(self, name, help="", labelnames=()) -> Counter:
        return self._ensure(Counter(name, help, labelnames))

    def ensure_gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._ensure(Gauge(name, help, labelnames))

    def ensure_histogram(self, name, help="", labelnames=(), buckets=None) -> Histogram:
        return self._ensure(Histogram(name, help, labelnames, buckets))

    # -- reads --

    def get(self, name: str) -> Optional[Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._instruments)

    def _sorted_instruments(self) -> List[Instrument]:
        with self._lock:
            return [self._instruments[n] for n in sorted(self._instruments)]

    def snapshot(self) -> Dict[str, dict]:
        """JSON-friendly view of every instrument's current series."""
        self.collect()
        out = {}
        for inst in self._sorted_instruments():
            out[inst.name] = {
                "type": inst.kind,
                "help": inst.help,
                "values": inst._snapshot_values(),
            }
        return out

    def export_state(self, collect: bool = True) -> Dict[str, dict]:
        """Raw serializable state of every instrument — the unit a node
        ships over the message plane for cluster aggregation
        (telemetry/aggregate.py). Plain dicts/lists/floats only, so the
        export survives the restricted wire unpickler and ``json.dumps``
        alike. Histograms keep raw bucket counts (mergeable); the
        derived-percentile view stays in :meth:`snapshot`.
        ``collect=False`` skips the collector pass — the history fold
        (telemetry/history.py) runs AS a collector and reading back
        through :meth:`collect` would recurse."""
        if collect:
            self.collect()
        return {
            inst.name: inst._export_decl()
            for inst in self._sorted_instruments()
        }

    def render_text(self) -> str:
        """Prometheus text exposition (one snapshot, trailing newline)."""
        self.collect()
        lines: List[str] = []
        for inst in self._sorted_instruments():
            if inst.help:
                lines.append(_help_line(inst.name, inst.help))
            lines.append(f"# TYPE {inst.name} {inst.kind}")
            lines.extend(inst._series_lines())
        return "\n".join(lines) + "\n" if lines else ""


# -- the process default registry (hung off Postoffice) --

_default_lock = threading.Lock()
_default_registry = MetricsRegistry()
_enabled = True


def default_registry() -> MetricsRegistry:
    with _default_lock:
        return _default_registry


def reset_default_registry() -> MetricsRegistry:
    """Swap in a fresh default registry (Postoffice.reset test hook).
    Instruments handed out from the old registry keep working but write
    to the orphaned registry — re-ensure after a reset."""
    global _default_registry
    with _default_lock:
        _default_registry = MetricsRegistry()
        return _default_registry


def set_enabled(flag: bool) -> bool:
    """Process-wide instrumentation switch; returns the previous value.
    Call sites cache their decision at construction time, so flip this
    BEFORE building the component under test."""
    global _enabled
    with _default_lock:
        prev = _enabled
        _enabled = bool(flag)
        return prev


def enabled() -> bool:
    with _default_lock:
        return _enabled
