"""Canonical metric catalog — the ONE place metric names are declared.

Instrumentation call sites fetch their instruments through these
accessors (idempotent ``ensure_*``: per-instance components — every
Executor, every parameter store — share the process-wide series and
distinguish themselves by label). ``install_all`` instantiates every
family against a registry; ``script/metrics_lint.py`` runs it on a fresh
registry to fail the build on duplicate or non-snake_case names, and
``doc/OBSERVABILITY.md`` documents the same names.
"""

from __future__ import annotations

from typing import Dict

from .registry import Counter, Gauge, Histogram, MetricsRegistry

# fine low-end buckets for host dispatch phases (queue-wait on an idle
# executor is single-digit microseconds)
PHASE_BUCKETS = (
    1e-6, 1e-5, 1e-4, 3.2e-4, 1e-3, 3.2e-3, 1e-2, 3.2e-2,
    1e-1, 3.2e-1, 1.0, 3.2, 10.0, 32.0, 100.0,
)


def executor_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Per-step executor phases + depth gauges (labeled by executor)."""
    return {
        "queue_wait": reg.ensure_histogram(
            "executor_queue_wait_seconds",
            "submit to dispatch-thread pickup, per step",
            labelnames=("executor",),
            buckets=PHASE_BUCKETS,
        ),
        "run": reg.ensure_histogram(
            "executor_run_seconds",
            "step body wall time on the dispatch thread (XLA dispatch, "
            "not device completion)",
            labelnames=("executor",),
            buckets=PHASE_BUCKETS,
        ),
        "materialize": reg.ensure_histogram(
            "executor_materialize_seconds",
            "block_until_ready wall time when the step's futures were "
            "forced (0 when nothing blocked)",
            labelnames=("executor",),
            buckets=PHASE_BUCKETS,
        ),
        "total": reg.ensure_histogram(
            "executor_step_total_seconds",
            "submit to finished (materialized), per step",
            labelnames=("executor",),
            buckets=PHASE_BUCKETS,
        ),
        "steps": reg.ensure_counter(
            "executor_steps_finished_total",
            "steps finished (ran + materialized)",
            labelnames=("executor",),
        ),
        "in_flight": reg.ensure_gauge(
            "executor_in_flight",
            "started (dispatched) but unfinished steps",
            labelnames=("executor",),
        ),
        "pending": reg.ensure_gauge(
            "executor_pending",
            "submitted steps not yet picked by the dispatch thread",
            labelnames=("executor",),
        ),
    }


def van_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Transport-layer byte counters (ref van.cc send_bytes_/recv_bytes_)."""
    return {
        "placed_bytes": reg.ensure_counter(
            "van_placed_bytes_total",
            "host arrays placed onto the device mesh (put_*)",
        ),
        "wire_sent_bytes": reg.ensure_counter(
            "van_wire_sent_bytes_total",
            "serialized frames leaving through transfer(), sender side",
        ),
        "wire_recv_bytes": reg.ensure_counter(
            "van_wire_recv_bytes_total",
            "serialized frames decoded by from_wire(), receiver side",
        ),
        "transfers": reg.ensure_counter(
            "van_transfers_total",
            "host wire transfers (request or response frames)",
        ),
    }


def parameter_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Push/Pull latency + key volume per store/channel (parameter layer)."""
    return {
        "push_latency": reg.ensure_histogram(
            "ps_push_latency_seconds",
            "push submit to finished, per request",
            labelnames=("store", "channel"),
        ),
        "pull_latency": reg.ensure_histogram(
            "ps_pull_latency_seconds",
            "pull submit to finished, per request",
            labelnames=("store", "channel"),
        ),
        "push_pull_latency": reg.ensure_histogram(
            "ps_push_pull_latency_seconds",
            "fused push_pull submit to finished, per request",
            labelnames=("store", "channel"),
        ),
        "push_keys": reg.ensure_counter(
            "ps_push_keys_total",
            "keys carried by push requests",
            labelnames=("store", "channel"),
        ),
        "pull_keys": reg.ensure_counter(
            "ps_pull_keys_total",
            "keys carried by pull requests",
            labelnames=("store", "channel"),
        ),
        "push_pull_keys": reg.ensure_counter(
            "ps_push_pull_keys_total",
            "keys carried by fused push_pull requests",
            labelnames=("store", "channel"),
        ),
    }


def kvops_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Device data-plane counters (ops/kv_ops + KeyDirectory slot cache).

    The donated-push counter and the fused-dispatch histogram size the
    zero-copy wins (doc/PERFORMANCE.md "Donation rules"); the slot-cache
    pair is the device analog of the reference's key-caching filter hit
    rate (src/filter/key_caching.h)."""
    return {
        "donated_pushes": reg.ensure_counter(
            "ps_kvops_donated_pushes_total",
            "table updates dispatched through a donated (in-place) "
            "push/push_pull — each one avoids a full [P, k] HBM copy",
        ),
        "fused_dispatch": reg.ensure_histogram(
            "ps_kvops_fused_dispatch_seconds",
            "host-side dispatch wall time of fused push_pull programs "
            "(one launch instead of a push + a pull)",
            buckets=PHASE_BUCKETS,
        ),
        "slot_cache_hits": reg.ensure_counter(
            "ps_directory_slot_cache_hits_total",
            "KeyDirectory.slots calls answered from the signature cache "
            "(hash/searchsorted and the device index upload skipped)",
        ),
        "slot_cache_misses": reg.ensure_counter(
            "ps_directory_slot_cache_misses_total",
            "KeyDirectory.slots calls that computed the slot mapping",
        ),
    }


#: stage labels the ingest pipeline records (doc/OBSERVABILITY.md):
#: read (source next/parse), filter (countmin tail-filter), prep
#: (localize/pack in the worker pool), upload (host→device staging)
INGEST_STAGES = ("read", "filter", "prep", "upload")


def ingest_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Host-ingest pipeline: per-stage latency, queue depth, volume.

    The ingest plane is the post-PR2 bottleneck (the device step is
    ~100x faster than the host→device transfer): these size where a
    training run's host seconds go — parse vs filter vs prep vs upload
    — and how full the pipeline's bounded queues run (a persistently
    empty queue means the stage upstream of it is the bottleneck)."""
    return {
        "stage_seconds": reg.ensure_histogram(
            "ps_ingest_stage_seconds",
            "per-minibatch wall time inside one ingest stage "
            "(read/filter/prep/upload)",
            labelnames=("stage",),
            buckets=PHASE_BUCKETS,
        ),
        "queue_depth": reg.ensure_gauge(
            "ps_ingest_queue_depth",
            "batches staged ahead of the consumer in an ingest queue, "
            "sampled at each emission",
            labelnames=("queue",),
        ),
        "examples": reg.ensure_counter(
            "ps_ingest_examples_total",
            "examples emitted by one ingest pipeline stage (host-side "
            "count, before device confirmation); chained pipelines — a "
            "reader feeding a train ingest — report each hop under its "
            "own label",
            labelnames=("pipeline",),
        ),
        "batches": reg.ensure_counter(
            "ps_ingest_batches_total",
            "minibatches emitted by one ingest pipeline stage",
            labelnames=("pipeline",),
        ),
        "uploaded_bytes": reg.ensure_counter(
            "ps_ingest_uploaded_bytes_total",
            "host bytes staged onto the device mesh by the ingest "
            "uploader (double-buffered device_put)",
        ),
    }


def wire_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Compact host→device wire (learner/wire.py): encoded bytes per
    encoding, bytes the encodings and the upload key cache kept off the
    link, encode cost, and cache traffic. The link-bound ceiling is
    bytes/example × link MB/s — these counters are its numerator."""
    return {
        "bytes": reg.ensure_counter(
            "ps_wire_bytes_total",
            "host bytes actually shipped (or queued to ship) on the "
            "host→device wire, by encoding mode",
            labelnames=("encoding",),
        ),
        "saved_bytes": reg.ensure_counter(
            "ps_wire_saved_bytes_total",
            "bytes kept OFF the wire vs the raw batch buffers — "
            "reason=encoding (compact formats) or cache_hit (a repeated "
            "array re-used its device-resident buffer)",
            labelnames=("reason",),
        ),
        "encode_seconds": reg.ensure_histogram(
            "ps_wire_encode_seconds",
            "per-batch wall time of the host-side wire encode (a "
            "stateless prep-pool stage — off the trainer thread)",
            buckets=PHASE_BUCKETS,
        ),
        "cache_hits": reg.ensure_counter(
            "ps_wire_cache_hits_total",
            "upload key-cache hits (crc32c signature routed, exact "
            "compare verified)",
        ),
        "cache_misses": reg.ensure_counter(
            "ps_wire_cache_misses_total",
            "upload key-cache misses (array uploaded and retained)",
        ),
        "fallbacks": reg.ensure_counter(
            "ps_wire_fallback_total",
            "batches an encoder refused (domain verify failed — ragged "
            "rows, non-sign labels, pinned-statics overflow, ...) and "
            "shipped on the raw wire instead, by reason; the "
            "verify-or-raw contract's visibility half",
            labelnames=("reason",),
        ),
    }


def serve_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Serving plane (serving/ — the request-path frontend): request
    volume + completion latency per kind, shed accounting by reason,
    the coalescer's merge economics, and read-replica traffic. The SLO
    view is ``ps_serve_latency_seconds`` p99 against
    ``ps_serve_shed_total`` — bounded tails are BOUGHT with explicit
    sheds (doc/SERVING.md, "Admission control")."""
    return {
        "requests": reg.ensure_counter(
            "ps_serve_requests_total",
            "requests admitted through the serving door, by kind "
            "(pull/predict/decode)",
            labelnames=("kind",),
        ),
        "shed": reg.ensure_counter(
            "ps_serve_shed_total",
            "requests rejected at admission (429-style), by reason: "
            "rate (token bucket empty) or queue (backlog past the "
            "depth bound)",
            labelnames=("reason",),
        ),
        "latency": reg.ensure_histogram(
            "ps_serve_latency_seconds",
            "request latency submit to completion, by kind — the "
            "serving SLO number (open-loop p50/p99 in bench records)",
            labelnames=("kind",),
            buckets=PHASE_BUCKETS,
        ),
        "queue_depth": reg.ensure_gauge(
            "ps_serve_queue_depth",
            "admitted, uncompleted requests (queued + executing), "
            "sampled at each admission",
        ),
        "coalesce_submits": reg.ensure_counter(
            "ps_serve_coalesce_submits_total",
            "merged pull windows flushed as ONE executor submit",
        ),
        "coalesce_merged_requests": reg.ensure_counter(
            "ps_serve_coalesce_merged_requests_total",
            "client pull requests carried by coalesced submits "
            "(merged/submits = the merge factor)",
        ),
        "coalesce_union_keys": reg.ensure_counter(
            "ps_serve_coalesce_union_keys_total",
            "deduped union keys actually pulled by coalesced submits "
            "(compare ps_pull_keys_total for the key dedup win)",
        ),
        "replica_hits": reg.ensure_counter(
            "ps_serve_replica_hits_total",
            "keys served from the read replica (no live-table touch)",
        ),
        "replica_misses": reg.ensure_counter(
            "ps_serve_replica_misses_total",
            "keys outside the hot-key replica, fallen through to a "
            "coalesced live pull",
        ),
        "replica_refresh": reg.ensure_histogram(
            "ps_serve_replica_refresh_seconds",
            "read-replica refresh wall time (the one serialization "
            "point with training pushes — off the request path)",
            buckets=PHASE_BUCKETS,
        ),
        "decode_tokens": reg.ensure_counter(
            "ps_serve_decode_tokens_total",
            "tokens generated by served decode requests "
            "(rows x steps, host-side count)",
        ),
        "batch_occupancy": reg.ensure_gauge(
            "ps_serve_batch_occupancy",
            "decode sessions resident in the continuous batch, sampled "
            "at every join and round boundary (occupancy/slots is the "
            "chip-fill ratio the batcher exists to raise)",
        ),
        "batch_joins": reg.ensure_counter(
            "ps_serve_batch_joins_total",
            "decode sessions joined into free batch slots at round "
            "boundaries (one per prompt row, not per request)",
        ),
        "batch_leaves": reg.ensure_counter(
            "ps_serve_batch_leaves_total",
            "batch slots released between rounds (EOS or token-budget "
            "retirement) — join/leave churn without stalling residents",
        ),
        "batch_rounds": reg.ensure_counter(
            "ps_serve_batch_rounds_total",
            "speculative rounds stepped over the shared batch (one "
            "target verify pass serves every resident session)",
        ),
        "batch_retired": reg.ensure_counter(
            "ps_serve_batch_retired_total",
            "decode sessions retired complete (their token stream is "
            "pinned identical to a sequential speculative run)",
        ),
        "degraded": reg.ensure_counter(
            "ps_serve_degraded_total",
            "requests that hit the degraded path after the live store "
            "failed or missed its deadline (503-style, DISTINCT from "
            "the admission 429s in ps_serve_shed_total): "
            "outcome=served (answered from the stale read replica "
            "inside the staleness bound) or outcome=error (DegradedError "
            "— no replica, too stale, or keys it cannot cover)",
            labelnames=("outcome",),
        ),
    }


#: update-path labels the FTRL dispatch records (ops/ftrl_sparse.py
#: resolve_update_path): pallas_sparse (fused sparse kernel),
#: xla_rows (gather→apply→scatter rows path), pallas_dense
#: (whole-shard Pallas sweep), ref (jnp/XLA dense reference)
FTRL_PATHS = ("pallas_sparse", "xla_rows", "pallas_dense", "ref")


def ftrl_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """FTRL update-path accounting (ops/ftrl.py + ops/ftrl_sparse.py).

    The path decision is STATIC per compiled step (a trace-time
    predicate — ``use_ref_path`` / ``use_sparse_kernel``), so these
    counters are incremented on the HOST at submit time (jit-purity:
    an in-kernel counter would fire once at trace and never again);
    they say which update formulation the training traffic actually
    rode, next to the ``ftrl_sparse`` A/B in bench records."""
    return {
        "rows": reg.ensure_counter(
            "ps_ftrl_rows_total",
            "state rows moved per submitted FTRL ministep — the "
            "deduped gather width (sparse formulations) or the "
            "whole-shard sweep width (dense)",
        ),
        "path": reg.ensure_counter(
            "ps_ftrl_update_path_total",
            "FTRL ministeps dispatched, by resolved update path "
            "(pallas_sparse / xla_rows / pallas_dense / ref)",
            labelnames=("path",),
        ),
    }


def device_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Device truth plane (telemetry/device.py): per-jit compile and
    recompile counts from the compiled-function inventory, the runtime
    donation-aliasing verifier, live roofline gauges (achieved GB/s /
    TFLOP/s and frac-of-peak against the benchmarks peak tables), and
    HBM/live-buffer accounting sampled by a registry collector. The
    ``fn`` label is the inventory name the wrap point declared
    (kv_push, step_encoded_scan.snap_donate, ...); ``resource`` is
    hbm or flops. A recompile RATE above noise is a storm (shape churn
    re-tracing every step — the configs/alerts/default.json rule); a
    donation fallback means XLA silently turned an in-place update
    into a whole-table copy (doc/PERFORMANCE.md "Donation rules")."""
    return {
        "compiles": reg.ensure_counter(
            "ps_device_compiles_total",
            "XLA compiles owned by the device inventory, per named "
            "function (first compile + every re-specialization)",
            labelnames=("fn",),
        ),
        "recompiles": reg.ensure_counter(
            "ps_device_recompiles_total",
            "compiles BEYOND a function's first — new avals or statics "
            "re-specialized an already-compiled entry point (zero on a "
            "healthy steady-state run after warmup)",
            labelnames=("fn",),
        ),
        "donation_fallbacks": reg.ensure_counter(
            "ps_device_donation_fallbacks_total",
            "compiles where a declared donation did not fully alias "
            "input to output (memory_analysis alias bytes below the "
            "donated argument bytes, or XLA's donated-buffers-unusable "
            "warning) — the update silently paid a copy",
            labelnames=("fn",),
        ),
        "dispatch_fallbacks": reg.ensure_counter(
            "ps_device_dispatch_fallbacks_total",
            "instrumented calls routed to the plain jit path (signature "
            "unreadable, or the compiled executable rejected the args) "
            "— correctness preserved, chip accounting skipped",
            labelnames=("fn",),
        ),
        "kernel_gb_s": reg.ensure_gauge(
            "ps_device_kernel_gb_s",
            "achieved HBM GB/s of the last sampled dispatch "
            "(cost-analysis bytes / measured wall time)",
            labelnames=("fn",),
        ),
        "kernel_tflops": reg.ensure_gauge(
            "ps_device_kernel_tflops",
            "achieved TFLOP/s of the last sampled dispatch "
            "(cost-analysis FLOPs / measured wall time)",
            labelnames=("fn",),
        ),
        "roofline_frac": reg.ensure_gauge(
            "ps_device_roofline_frac",
            "achieved fraction of this chip's peak for one resource "
            "(hbm: of HBM_PEAK_GB_S; flops: MFU vs FLOPS_PEAK_TFLOPS); "
            "absent on device kinds the peak tables do not know",
            labelnames=("fn", "resource"),
        ),
        "hbm_bytes_in_use": reg.ensure_gauge(
            "ps_device_hbm_bytes_in_use",
            "allocator bytes in use on the device at last collection "
            "(memory_stats; TPU backends)",
            labelnames=("device",),
        ),
        "hbm_high_water": reg.ensure_gauge(
            "ps_device_hbm_high_water_bytes",
            "allocator peak bytes in use since process start "
            "(memory_stats peak_bytes_in_use)",
            labelnames=("device",),
        ),
        "hbm_limit": reg.ensure_gauge(
            "ps_device_hbm_bytes_limit",
            "allocator byte limit for the device (memory_stats)",
            labelnames=("device",),
        ),
        "hbm_frac_used": reg.ensure_gauge(
            "ps_device_hbm_frac_used",
            "bytes_in_use / bytes_limit at last collection — the "
            "gauge the HBM high-water alert rule watches",
            labelnames=("device",),
        ),
        "live_buffers": reg.ensure_gauge(
            "ps_device_live_buffer_bytes",
            "total nbytes of live jax arrays at last collection "
            "(jax.live_arrays — works on every backend, CPU included)",
        ),
        "live_high_water": reg.ensure_gauge(
            "ps_device_live_buffer_high_water_bytes",
            "process-lifetime high-water mark of the live-buffer total",
        ),
    }


def recovery_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Failure detection → recovery orchestration (system/recovery.py +
    the chaos plane, doc/ROBUSTNESS.md). ``RecoveryCoordinator.check``
    used to only log; these make detection volume, handler health and
    recovery latency visible to every snapshot — the drill's MTTR has
    a live counterpart."""
    return {
        "deaths": reg.ensure_counter(
            "ps_recovery_deaths_total",
            "nodes declared dead by the recovery coordinator (first "
            "detection only; revive + re-death counts again), by role",
            labelnames=("role",),
        ),
        "handler_failures": reg.ensure_counter(
            "ps_recovery_handler_failures_total",
            "recovery handler invocations that still failed after "
            "exhausting their retry policy (utils/retry.py backoff)",
        ),
        "seconds": reg.ensure_histogram(
            "ps_recovery_seconds",
            "wall time of one dead node's full recovery handling "
            "(every registered handler, retries included)",
            buckets=PHASE_BUCKETS,
        ),
    }


def node_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Per-node resource metrics for the cluster metrics plane
    (system/aux_runtime.py): each registered node owns a PRIVATE
    registry holding this family, refreshed from its HeartbeatReport at
    every metric report and shipped over the message plane for
    node-labeled aggregation (telemetry/aggregate.py — the ``node``
    label is added by the aggregator, which is why the family itself is
    unlabeled). Counters track the sampler's LIFETIME totals so
    cross-node sums stay monotone."""
    return {
        "heartbeats": reg.ensure_counter(
            "ps_node_heartbeats_total",
            "metric reports this node shipped onto the cluster plane",
        ),
        "busy": reg.ensure_counter(
            "ps_node_busy_seconds_total",
            "lifetime busy-timer seconds (HeartbeatInfo start/stop_timer)",
        ),
        "net_in": reg.ensure_counter(
            "ps_node_net_in_bytes_total",
            "lifetime bytes received by this node (Van transfer accounting)",
        ),
        "net_out": reg.ensure_counter(
            "ps_node_net_out_bytes_total",
            "lifetime bytes sent by this node (Van transfer accounting)",
        ),
        "rss_mb": reg.ensure_gauge(
            "ps_node_rss_mb",
            "resident set size at the node's last report (MB)",
        ),
        "cpu": reg.ensure_gauge(
            "ps_node_cpu_usage",
            "process cpu usage over the node's last report window "
            "(1.0 = one core)",
        ),
        "host_cpu": reg.ensure_gauge(
            "ps_node_host_cpu_usage",
            "whole-host cpu usage over the node's last report window",
        ),
        "uptime": reg.ensure_gauge(
            "ps_node_uptime_seconds",
            "seconds since the node's sampler started",
        ),
        "report_interval": reg.ensure_histogram(
            "ps_node_report_interval_seconds",
            "observed gap between this node's consecutive metric "
            "reports (bucket-merged across nodes in the cluster view)",
            buckets=PHASE_BUCKETS,
        ),
    }


def cluster_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """The aggregator's own health series (telemetry/aggregate.py):
    per-node liveness of the METRICS PLANE itself — rendered at the top
    of every /metrics scrape so a frozen node is marked
    (``ps_cluster_node_up 0`` + its report age) instead of its last
    values silently reading as current."""
    return {
        "nodes": reg.ensure_gauge(
            "ps_cluster_nodes",
            "nodes the aggregator has ever heard from (and not forgotten)",
        ),
        "node_up": reg.ensure_gauge(
            "ps_cluster_node_up",
            "1 while the node's last metric report is younger than the "
            "staleness window, else 0 (stale/dead)",
            labelnames=("node",),
        ),
        "report_age": reg.ensure_gauge(
            "ps_cluster_report_age_seconds",
            "age of the node's newest metric report at scrape time",
            labelnames=("node",),
        ),
        "reports": reg.ensure_counter(
            "ps_cluster_reports_total",
            "metric reports merged per node",
            labelnames=("node",),
        ),
        "conflicts": reg.ensure_counter(
            "ps_cluster_merge_conflicts_total",
            "distinct (node, metric) pairs rejected from the merge "
            "because the node re-declared the metric with a different "
            "kind or bucket layout (mis-merging would be worse than "
            "dropping; deduped — one persistently-bad export counts "
            "once, not once per scrape)",
        ),
    }


def blackbox_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Flight recorder (telemetry/blackbox.py): ring absorption volume
    and occupancy. The per-event hot path never touches the registry —
    these publish LAZILY from the sample/dump paths (the catalog's one
    deliberately-coarse family: a counter that lags by up to one
    metrics-sample interval, bought for a sub-noise-floor emit path)."""
    return {
        "events": reg.ensure_counter(
            "ps_blackbox_events_total",
            "span events absorbed by the flight-recorder ring "
            "(published lazily at sample/dump time, not per event)",
        ),
        "samples": reg.ensure_counter(
            "ps_blackbox_metrics_samples_total",
            "periodic metrics-delta samples recorded into the ring",
        ),
        "ring_events": reg.ensure_gauge(
            "ps_blackbox_ring_events",
            "events currently held by this process's recorder ring "
            "(<= capacity; older events have been evicted)",
        ),
    }


def bundle_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Diagnostic-bundle trigger plane (telemetry/blackbox.py):
    capture volume per trigger kind, rate-limit suppressions, capture
    cost. ``trigger`` is the closed KIND set (alert / degraded /
    node_death / executor_wait_timeout / scrape / manual — never the
    rule or node name, which would be unbounded label cardinality)."""
    return {
        "captures": reg.ensure_counter(
            "ps_bundle_captures_total",
            "diagnostic bundles captured, by trigger kind",
            labelnames=("trigger",),
        ),
        "suppressed": reg.ensure_counter(
            "ps_bundle_suppressed_total",
            "auto-capture triggers suppressed by the rate limit "
            "(a trigger storm costs one bundle, not one per symptom)",
        ),
        "capture_seconds": reg.ensure_histogram(
            "ps_bundle_capture_seconds",
            "wall time of one full bundle capture (ring fetches over "
            "the Van included)",
            buckets=PHASE_BUCKETS,
        ),
        "last_ring_nodes": reg.ensure_gauge(
            "ps_bundle_last_ring_nodes",
            "nodes represented (ring dump or staleness entry) in the "
            "most recent bundle",
        ),
    }


#: alert states exported by ps_alert_state (telemetry/alerts.py):
#: 0 inactive, 1 pending (condition holding, for_s not yet elapsed),
#: 2 firing, 3 resolved (recently cleared, held resolve_hold_s)
ALERT_STATES = ("inactive", "pending", "firing", "resolved")


def alert_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """SLO alerting (telemetry/alerts.py): each rule's live state and
    its transition history as counters — scrapers page on
    ``ps_alert_state == 2`` and the dashboard event log carries the
    same transitions for humans."""
    return {
        "state": reg.ensure_gauge(
            "ps_alert_state",
            "alert rule state: 0 inactive / 1 pending / 2 firing / "
            "3 resolved (recently cleared)",
            labelnames=("rule",),
        ),
        "transitions": reg.ensure_counter(
            "ps_alert_transitions_total",
            "alert state transitions, by rule and destination state",
            labelnames=("rule", "to"),
        ),
        # meta-monitoring (who watches the watcher): the evaluator's
        # own duration and schedule lag — the alert_evaluator_starved
        # default rule fires on the lag gauge
        "eval_seconds": reg.ensure_histogram(
            "ps_alert_eval_seconds",
            "wall seconds one alert-evaluation tick took (sample + "
            "every rule's compute + state advance)",
        ),
        "eval_lag": reg.ensure_gauge(
            "ps_alert_eval_lag_seconds",
            "seconds the latest evaluation started BEHIND its expected "
            "period (gap since the previous tick minus the period, "
            "floored at 0) — sustained lag means the evaluator thread "
            "is starving and alerts are going blind",
        ),
    }


def history_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """History plane (telemetry/history.py): the multi-resolution ring
    cascade's own accounting — fold cost, series occupancy, and the
    cardinality escape valve. ``dropped`` is the loud signal that a
    label explosion hit the caps: rings stay bounded, the overflow
    series lose history (never memory)."""
    return {
        "folds": reg.ensure_counter(
            "ps_history_folds_total",
            "registry-state folds landed in the ring cascade",
        ),
        "fold_seconds": reg.ensure_histogram(
            "ps_history_fold_seconds",
            "wall seconds one history fold took (read the registry "
            "export + update every resolution level)",
        ),
        "series": reg.ensure_gauge(
            "ps_history_series",
            "series currently tracked by the ring cascade",
        ),
        "dropped": reg.ensure_counter(
            "ps_history_dropped_series_total",
            "series REFUSED by the cardinality caps (per-metric or "
            "process-wide), by metric — each distinct series counts "
            "once; nonzero means some label set has no history",
            labelnames=("metric",),
        ),
        "collect_seconds": reg.ensure_gauge(
            "ps_registry_collect_seconds",
            "wall seconds the registry's last collector pass took "
            "(every snapshot/scrape runs it; the history fold "
            "publishes the registry's own measurement)",
        ),
    }


#: realized-staleness buckets (ps_learning_staleness): integer ministep
#: counts land between the .5 edges, so each small staleness value gets
#: its own bucket up to the configured-τ range anyone sanely runs
STALENESS_BUCKETS = (
    0.5, 1.5, 2.5, 3.5, 4.5, 6.5, 8.5, 12.5, 16.5, 24.5, 32.5, 48.5, 64.5,
)

#: reasons the divergence counter ticks (telemetry/learning.py):
#: nonfinite (NaN/Inf loss or gradient) or spike (grad norm far past
#: its recent median)
DIVERGENCE_REASONS = ("nonfinite", "spike")


def learning_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Learning truth plane (telemetry/learning.py): the staleness the
    bounded-delay contract actually REALIZES (vs the configured
    ``SGDConfig.max_delay`` τ), per-server-shard key heat from the
    windowed count sketch, and the convergence trajectory metered
    host-side from the step builders' in-jit side outputs. Five planes
    watch the system (seconds, bytes, FLOPs, incidents); this family
    watches the learning — a NaN'd table or a τ breach becomes a
    metric, an alert rule, and a bench-record section instead of a
    silent 200."""
    return {
        "staleness": reg.ensure_histogram(
            "ps_learning_staleness_ministeps",
            "realized weight-snapshot staleness of one submitted step, "
            "in ministeps since the snapshot was pulled (the "
            "bounded-delay contract's MEASURED side; observed max must "
            "stay <= the configured SGDConfig.max_delay)",
            labelnames=("worker",),
            buckets=STALENESS_BUCKETS,
        ),
        "staleness_max": reg.ensure_gauge(
            "ps_learning_staleness_max",
            "largest realized staleness this worker has observed "
            "(ministeps; process lifetime)",
            labelnames=("worker",),
        ),
        "staleness_over_tau": reg.ensure_gauge(
            "ps_learning_staleness_over_tau",
            "worst per-submission margin of realized staleness over the "
            "LIVE effective τ in force at submit time (the adaptive "
            "controller's bound when tau_adaptive, else the configured "
            "max_delay) — <= 0 while the bounded-delay contract holds; "
            "> 0 is a contract breach (the staleness_breach alert rule "
            "fires on this gauge)",
            labelnames=("worker",),
        ),
        "examples": reg.ensure_counter(
            "ps_learning_examples_total",
            "device-confirmed training examples folded into the "
            "progress plane by ISGDCompNode.collect (the step's own "
            "num_ex output, not a host-side submission count)",
            labelnames=("worker",),
        ),
        "loss": reg.ensure_gauge(
            "ps_learning_loss",
            "per-example training loss of the worker's last collected "
            "step (objective / num_ex)",
            labelnames=("worker",),
        ),
        "grad_norm": reg.ensure_gauge(
            "ps_learning_grad_norm",
            "L2 norm of the last collected step's per-worker gradient "
            "contributions (sqrt of the in-jit grad_sq side output)",
            labelnames=("worker",),
        ),
        "update_norm": reg.ensure_gauge(
            "ps_learning_update_norm",
            "L2 norm of the aggregated (post-filter) update handed to "
            "the updater on the last collected step",
            labelnames=("worker",),
        ),
        "weight_norm": reg.ensure_gauge(
            "ps_learning_weight_norm",
            "L2 magnitude of the weights the last collected step "
            "consumed (per-occurrence touched weights, not the global "
            "table norm — a blow-up detector and trend line)",
            labelnames=("worker",),
        ),
        "divergence": reg.ensure_counter(
            "ps_learning_divergence_total",
            "collected steps judged divergent host-side, by reason: "
            "nonfinite (NaN/Inf loss or gradient) or spike (grad norm "
            "far past its recent median) — the loss_divergence alert "
            "rule fires on this counter's rate",
            labelnames=("worker", "reason"),
        ),
        "heat_slots": reg.ensure_counter(
            "ps_learning_heat_slots_total",
            "slot observations folded into the key-heat sketch "
            "(pushed/pulled slots noted on the feeder/uploader threads)",
            labelnames=("worker",),
        ),
        "shard_share": reg.ensure_gauge(
            "ps_learning_shard_share",
            "this server shard's fraction of the windowed key-heat "
            "load (sums to ~1 across shards while traffic flows) — the "
            "direct input a declarative partitioner rebalances on",
            labelnames=("shard",),
        ),
        "shard_imbalance": reg.ensure_gauge(
            "ps_learning_shard_imbalance",
            "max/mean of per-shard windowed key-heat load — 1.0 is "
            "perfectly balanced; the shard_imbalance alert rule fires "
            "past its threshold",
        ),
    }


def partition_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Declarative partitioning + heat-driven live repartitioning
    (parallel/partition.py RebalanceController, KVVector.migrate)."""
    return {
        "rebalances": reg.ensure_counter(
            "ps_partition_rebalances_total",
            "live rebalances executed (shard_imbalance-triggered or "
            "forced): one consistent-snapshot migration each",
        ),
        "rows_moved": reg.ensure_counter(
            "ps_partition_rows_moved_total",
            "table rows relocated across server key ranges by live "
            "rebalances (hot slots + the cold slots they swapped with)",
        ),
        "migration_seconds": reg.ensure_histogram(
            "ps_partition_migration_seconds",
            "wall seconds per online migration: snapshot barrier -> "
            "host permute -> install + journal replay + directory flip",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0),
        ),
        "post_imbalance": reg.ensure_gauge(
            "ps_partition_post_imbalance",
            "max/mean shard load imbalance after the latest rebalance "
            "(plan prediction, replaced by the re-measured value once "
            "post-rebalance traffic flows) — should sit below the "
            "shard_imbalance alert threshold",
        ),
    }


def consistency_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Self-driving consistency (learner/consistency.py): the adaptive
    τ controller's live bound + reactions, and the KKT significance
    filter's key accounting. The suppression counters reconcile
    in-record against ``ps_push_keys_total``:
    pushed + suppressed == candidates (the in-jit mask), and
    candidates + dropped == the unfiltered baseline (the host-side
    persistent-drop set) — bench records assert both identities."""
    return {
        "tau": reg.ensure_gauge(
            "ps_consistency_tau",
            "the LIVE effective bounded-delay τ this worker submits "
            "under right now (== configured max_delay while static; "
            "the AdaptiveTauController moves it between submissions)",
            labelnames=("worker",),
        ),
        "tau_changes": reg.ensure_counter(
            "ps_consistency_tau_changes_total",
            "τ moves the adaptive controller made, by direction: widen "
            "(stability-earned async headroom), clamp (grad-norm spike "
            "backoff), reset (divergence reaction to τ=0)",
            labelnames=("worker", "direction"),
        ),
        "suppressed": reg.ensure_counter(
            "ps_consistency_suppressed_keys_total",
            "unique slots the in-jit KKT mask suppressed from pushes "
            "(w == 0 and |z + g| inside the scaled L1 dead zone, net "
            "of the seeded starvation escape)",
            labelnames=("worker",),
        ),
        "candidates": reg.ensure_counter(
            "ps_consistency_candidate_keys_total",
            "unique real (non-padding) slots the filtered sparse step "
            "considered — pushed keys + suppressed keys must equal "
            "this (the in-record reconciliation identity)",
            labelnames=("worker",),
        ),
        "dropped": reg.ensure_counter(
            "ps_consistency_dropped_keys_total",
            "slot occurrences removed from batches HOST-SIDE before "
            "prep because the slot's suppression streak crossed "
            "kkt_drop_after (these never cost upload keys or bytes; "
            "periodically revisited via kkt_revisit_every)",
            labelnames=("worker",),
        ),
        "backoff": reg.ensure_counter(
            "ps_consistency_backoff_total",
            "automatic LR backoffs the divergence reaction applied "
            "(each also clamps τ to 0 and re-jits the weights fn)",
            labelnames=("worker",),
        ),
        "rollback": reg.ensure_counter(
            "ps_consistency_rollback_total",
            "snapshot rollbacks the divergence reaction executed, by "
            "trigger reason (nonfinite, spike, alert) — state restored "
            "to the controller's last healthy in-memory snapshot",
            labelnames=("worker", "reason"),
        ),
        "snapshot_age": reg.ensure_gauge(
            "ps_consistency_snapshot_age_steps",
            "collected steps since the controller's last healthy "
            "rollback snapshot (the rollback blast radius if the next "
            "collect diverges)",
            labelnames=("worker",),
        ),
    }


def app_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Application layer: RPC fan-out and training volume."""
    return {
        "rpcs": reg.ensure_counter(
            "ps_rpc_total",
            "ps.submit group RPCs delivered (request+auto-ack pairs)",
        ),
        "examples": reg.ensure_counter(
            "app_examples_total",
            "training examples submitted to device steps",
        ),
    }


def heartbeat_instruments(reg: MetricsRegistry) -> Dict[str, object]:
    """Node liveness/traffic as last-report gauges (aux_runtime.beat)."""
    return {
        "reports": reg.ensure_counter(
            "heartbeat_reports_total",
            "heartbeat reports collected",
            labelnames=("node",),
        ),
        "net_in_mb": reg.ensure_gauge(
            "node_net_in_mb",
            "bytes received since the node's previous report (MB)",
            labelnames=("node",),
        ),
        "net_out_mb": reg.ensure_gauge(
            "node_net_out_mb",
            "bytes sent since the node's previous report (MB)",
            labelnames=("node",),
        ),
    }


def _cached_family(family_fn):
    """Process-default accessor for one instrument family: returns a
    zero-arg callable yielding the family's instruments against the
    CURRENT default registry, or None while telemetry is disabled.
    The (registry, instruments) pair is cached per accessor and
    re-ensured only when tests swap the default registry
    (Postoffice.reset) — the call sites are hot paths (kv_ops pushes,
    per-request admission/coalescer stages, per-batch wire encodes)
    that must not re-ensure the family per call."""
    cache = (None, None)

    def accessor():
        nonlocal cache
        from . import registry as telemetry_registry

        if not telemetry_registry.enabled():
            return None
        reg = telemetry_registry.default_registry()
        if cache[0] is not reg:
            cache = (reg, family_fn(reg))
        return cache[1]

    accessor.__name__ = f"cached_{family_fn.__name__}"
    accessor.__qualname__ = accessor.__name__
    accessor.__doc__ = (
        f"Process-default {family_fn.__name__} (hot-path cache), or "
        "None when telemetry is off."
    )
    return accessor


# the one cache per hot-path family: data plane (kv_ops pushes,
# KVMap/KVLayer steps, KeyDirectory slot cache), request path
# (admission, coalescer, replica, frontend workers), wire
# (encode_exact, UploadCache), and the per-ministep FTRL path counter
# (AsyncSGDWorker._submit_prepped)
cached_kvops_instruments = _cached_family(kvops_instruments)
cached_serve_instruments = _cached_family(serve_instruments)
cached_wire_instruments = _cached_family(wire_instruments)
cached_ftrl_instruments = _cached_family(ftrl_instruments)
cached_device_instruments = _cached_family(device_instruments)
cached_learning_instruments = _cached_family(learning_instruments)
cached_blackbox_instruments = _cached_family(blackbox_instruments)
cached_bundle_instruments = _cached_family(bundle_instruments)
cached_partition_instruments = _cached_family(partition_instruments)
cached_consistency_instruments = _cached_family(consistency_instruments)


INSTRUMENT_FAMILIES = (
    executor_instruments,
    van_instruments,
    parameter_instruments,
    kvops_instruments,
    ingest_instruments,
    wire_instruments,
    serve_instruments,
    ftrl_instruments,
    device_instruments,
    learning_instruments,
    recovery_instruments,
    node_instruments,
    cluster_instruments,
    alert_instruments,
    history_instruments,
    blackbox_instruments,
    bundle_instruments,
    partition_instruments,
    consistency_instruments,
    app_instruments,
    heartbeat_instruments,
)


def install_all(reg: MetricsRegistry) -> Dict[str, object]:
    """Instantiate every declared instrument (metrics-lint entry point).
    Raises on duplicate names or declaration mismatches across families;
    returns name → instrument."""
    out: Dict[str, object] = {}
    for family in INSTRUMENT_FAMILIES:
        for inst in family(reg).values():
            out[inst.name] = inst
    return out
