"""Device truth plane: per-jit compile inventory, recompile/donation
tracking, roofline gauges, and HBM accounting.

Every observability layer before this one (the PR 1 registry, the PR 7
timeline, the PR 10 cluster plane) sees the *host*: spans and counters
say when a dispatch left and when its future resolved, but the chip
itself stays a black box — which is why the ROADMAP's roofline items
still quote hand-built bytes models. This module makes chip-side facts
first-class:

- **Compiled-function inventory** (:class:`DeviceInventory` /
  :func:`instrument`): wraps a jitted entry point so every
  ``lower().compile()`` is *owned* by the inventory. Per named
  function it records XLA ``cost_analysis()`` FLOPs / bytes-accessed
  and ``memory_analysis()`` buffer sizes, detects recompiles (a call
  with new avals/statics → ``ps_device_recompiles_total{fn}``), and
  verifies that declared donation actually aliased
  (``memory_analysis().alias_size_in_bytes`` against the donated
  argument bytes + the XLA "donated buffers were not usable" warning
  → ``ps_device_donation_fallbacks_total{fn}``) — the runtime twin of
  the static donation lint (doc/PERFORMANCE.md "Donation rules").
- **Roofline gauges**: with sampling enabled
  (:func:`set_sampling`), every N-th instrumented dispatch is timed to
  device completion; achieved GB/s and TFLOP/s derive from the
  cost-analysis bytes/FLOPs and land as
  ``ps_device_kernel_gb_s{fn}`` / ``ps_device_kernel_tflops{fn}``,
  with ``ps_device_roofline_frac{fn,resource}`` against the
  ``benchmarks.HBM_PEAK_GB_S`` / ``FLOPS_PEAK_TFLOPS`` peak tables
  (unknown device kinds report no frac, never a faked one).
- **HBM accounting** (:class:`HbmMonitor`): a registry collector
  sampling ``jax.local_devices()[*].memory_stats()`` (bytes in use /
  peak / limit, TPU backends) and the live-buffer total from
  ``jax.live_arrays()`` with a process-lifetime high-water mark —
  the ``ps_device_hbm_*`` / ``ps_device_live_buffer_*`` families.

Dispatch semantics: the wrapper maintains its own signature →
``Compiled`` cache and calls the compiled executable directly, so
instrumentation adds no second compile. The original jitted callable
is kept as the safety net: calls whose signature cannot be read
(foreign leaf types), tracer-stage calls (the function inlined inside
an enclosing jit), and compiled-dispatch failures (e.g. a sharding the
lowering was not specialized for) all fall through to the plain jit
path bit-identically, counted under
``ps_device_dispatch_fallbacks_total{fn}``. Statics must be passed as
keyword arguments at instrumented call sites (true for every wrap
point: ops/kv_ops, ops jit entry points, the async_sgd step builders).

``bench.py`` embeds :func:`snapshot` as the ``device`` section of
every record; ``doc/OBSERVABILITY.md`` ("Device truth plane")
documents how to read it.
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Dict, Optional, Sequence, Tuple

from . import registry as telemetry_registry

#: substring of the jax warning emitted when a declared donation could
#: not alias (shape/dtype mismatch, or a backend without donation)
_DONATION_WARNING = "donated buffers were not usable"


def _peaks(device_kind: str) -> Tuple[Optional[float], Optional[float]]:
    """(HBM peak GB/s, bf16 peak TFLOP/s) for a device kind, or Nones."""
    from ..benchmarks import FLOPS_PEAK_TFLOPS, HBM_PEAK_GB_S

    return HBM_PEAK_GB_S.get(device_kind), FLOPS_PEAK_TFLOPS.get(device_kind)


def _leaf_sig(leaf) -> Tuple:
    """Hashable signature of one pytree leaf: (shape, dtype, weak_type,
    sharding). Sharding is part of the key because a Compiled is
    specialized to the shardings it was lowered with — two same-aval
    call patterns with different shardings need their own entries, or
    the second would raise (and fall back) on every dispatch."""
    import jax

    aval = jax.api_util.shaped_abstractify(leaf)
    sharding = getattr(leaf, "sharding", None)
    return (
        aval.shape,
        str(aval.dtype),
        bool(getattr(aval, "weak_type", False)),
        sharding,
    )


def _static_key(value) -> Any:
    """Statics are hashable by jit's contract; an unhashable oddity
    degrades to repr rather than poisoning the cache key."""
    try:
        hash(value)
        return value
    except TypeError:
        return repr(value)


def _canonical_call(sig, statics, args, kwargs):
    """Bind a call against the function's signature, apply declared
    defaults, and split it into ``(dyn_args, dyn_kwargs,
    static_vals)`` — statics extracted BY NAME regardless of how the
    caller spelled them. This mirrors jit's own cache normalization:
    ``f(x)``, ``f(x, seed_default)`` and ``f(x, k=<default>)`` all
    resolve to one canonical form, so an omitted default vs its
    explicit spelling cannot double-compile (and tick a spurious
    recompile). Returns None when binding fails — the caller then uses
    the raw call shape and the jit raises its own arity error."""
    import inspect

    try:
        bound = sig.bind(*args, **kwargs)
        bound.apply_defaults()
    except TypeError:
        return None
    P = inspect.Parameter
    dyn_args: list = []
    dyn_kwargs: Dict[str, Any] = {}
    static_vals: list = []
    for pname, param in sig.parameters.items():
        if pname not in bound.arguments:
            continue
        v = bound.arguments[pname]
        if pname in statics:
            static_vals.append((pname, v))
        elif param.kind in (P.POSITIONAL_ONLY, P.POSITIONAL_OR_KEYWORD):
            dyn_args.append(v)
        elif param.kind == P.VAR_POSITIONAL:
            dyn_args.extend(v)
        elif param.kind == P.KEYWORD_ONLY:
            dyn_kwargs[pname] = v
        else:  # VAR_KEYWORD: a dict of extra keywords
            for k, vv in v.items():
                if k in statics:
                    static_vals.append((k, vv))
                else:
                    dyn_kwargs[k] = vv
    static_vals.sort(key=lambda kv: kv[0])
    return tuple(dyn_args), dyn_kwargs, tuple(static_vals)


def _cost_dict(compiled) -> Optional[Dict[str, float]]:
    """Normalized ``cost_analysis()``: {"flops", "bytes_accessed"} or
    None when the backend offers no analysis."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out: Dict[str, float] = {}
    if ca.get("flops") is not None:
        out["flops"] = float(ca["flops"])
    if ca.get("bytes accessed") is not None:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    return out or None


def _memory_dict(compiled) -> Optional[Dict[str, int]]:
    """Normalized ``memory_analysis()`` buffer sizes, or None."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    try:
        return {
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
    except Exception:
        return None


def aot_analyze(jit_fn, *args, **kwargs) -> Optional[Dict[str, Any]]:
    """One-shot AOT analysis of a jitted callable at concrete args:
    ``{"flops", "bytes_accessed", "argument_bytes", ..., "donation_
    warned"}`` via ``lower().compile()``, or None when the backend
    cannot lower/analyze. Pays one compile; bench cross-checks
    (components.ftrl_sparse_ab, the flash probe) use this to put the
    XLA-derived bytes/FLOPs next to their hand models."""
    try:
        with warnings.catch_warnings(record=True) as wlist:
            warnings.simplefilter("always")
            compiled = jit_fn.lower(*args, **kwargs).compile()
        out: Dict[str, Any] = {
            "donation_warned": any(
                _DONATION_WARNING in str(w.message) for w in wlist
            ),
        }
        cost = _cost_dict(compiled)
        if cost:
            out.update(cost)
        mem = _memory_dict(compiled)
        if mem:
            out.update(mem)
        return out
    except Exception:
        return None


class _FnRecord:
    """Inventory state of one named function (all fields guarded by
    the owning inventory's lock)."""

    __slots__ = (
        "name", "compiles", "recompiles", "donation_fallbacks",
        "dispatch_fallbacks", "calls", "cost", "memory",
        "donated_bytes", "last_timing",
    )

    def __init__(self, name: str):
        self.name = name
        self.compiles = 0
        self.recompiles = 0
        self.donation_fallbacks = 0
        self.dispatch_fallbacks = 0
        self.calls = 0
        self.cost: Optional[Dict[str, float]] = None      # latest compile
        self.memory: Optional[Dict[str, int]] = None      # latest compile
        self.donated_bytes = 0                            # latest compile
        self.last_timing: Optional[Dict[str, Any]] = None # latest sample

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "compiles": self.compiles,
            "recompiles": self.recompiles,
            "donation_fallbacks": self.donation_fallbacks,
            "calls": self.calls,
        }
        if self.dispatch_fallbacks:
            out["dispatch_fallbacks"] = self.dispatch_fallbacks
        if self.cost:
            out["cost"] = dict(self.cost)
        if self.memory:
            out["memory"] = dict(self.memory)
        if self.donated_bytes:
            out["donated_bytes"] = self.donated_bytes
        if self.last_timing:
            out["roofline"] = dict(self.last_timing)
        return out


def _device_tel():
    """The ps_device_* instruments against the current default
    registry, or None while telemetry is off (hot-path cached)."""
    from .instruments import cached_device_instruments

    return cached_device_instruments()


class _WrapperCache(dict):
    """A wrapper-local signature → Compiled dict. A plain dict is not
    weakref-able; the inventory holds these by weakref so reset() can
    clear live caches without keeping dead wrappers' executables
    alive."""

    __slots__ = ("__weakref__",)


class DeviceInventory:
    """Per-function chip-truth records + the instrument() wrap factory.

    Each wrapper owns its OWN signature → Compiled cache (a closure
    dict): when a wrapper and its jit are dropped — a rebuilt step
    builder, a dead worker — the cached executables die with them,
    exactly jax's own cache-lifetime semantics (a process-global cache
    would strongly leak every executable of every builder ever made).
    The inventory holds only the small per-NAME records. Thread-safe:
    compiles happen outside the lock (they are seconds on a real chip;
    serializing them would wedge concurrent call sites), bookkeeping
    inside it — a racing duplicate compile records once — and the
    steady-state dispatch path takes NO inventory lock (dict read +
    benign GIL-atomic counters).
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: Dict[str, _FnRecord] = {}       # guarded-by: _lock
        # read lock-free on every dispatch (a GIL-atomic int; set under
        # the lock only for write ordering) — sampling cadence is
        # advisory, a stale read costs at most one mistimed sample
        self._sample_every = 0
        self._warmup_marks: Dict[str, Tuple[int, int]] = {}  # guarded-by: _lock
        # WEAK refs to live wrapper caches, so reset() can clear them
        # (module-level wrappers like kv_ops outlive any test) without
        # the inventory owning their lifetime — a dead wrapper's cache,
        # executables included, is garbage the moment the wrapper is
        self._cache_refs: list = []                    # guarded-by: _lock

    # -- configuration ----------------------------------------------------

    def set_sampling(self, every: int) -> int:
        """Time every N-th instrumented dispatch to device completion
        for the roofline gauges (0 disables — the production default:
        a timed call blocks on its result, which an async pipeline
        should only pay when someone is measuring). Returns the
        previous value so benches can restore it."""
        with self._lock:
            prev, self._sample_every = self._sample_every, max(0, int(every))
        return prev

    def mark_warmup(self) -> None:
        """Record current compile/recompile counts per function; the
        snapshot's ``recompiles_post_warmup`` counts only growth past
        this mark (the steady-state contract: zero after warmup)."""
        with self._lock:
            self._warmup_marks = {
                name: (rec.compiles, rec.recompiles)
                for name, rec in self._records.items()
            }

    def reset(self) -> None:
        """Test hook: clear the per-name records, warmup marks, and
        every LIVE wrapper's compiled cache (so a module-level wrapper
        like kv_ops recompiles — and re-registers its record — on its
        next call). Dead wrappers' caches are already garbage."""
        with self._lock:
            self._records.clear()
            self._warmup_marks.clear()
            live = []
            for ref in self._cache_refs:
                cache = ref()
                if cache is not None:
                    cache.clear()
                    live.append(ref)
            self._cache_refs = live

    # -- the wrapper ------------------------------------------------------

    def instrument(
        self,
        name: str,
        fn,
        static_argnames: Sequence[str] = (),
        donate_argnums: Sequence[int] = (),
    ):
        """Wrap a jitted callable into the inventory under ``name``.

        ``static_argnames`` must mirror the jit's own declaration and
        the call sites must pass those as keywords (every wrap point in
        this repo does). ``donate_argnums`` mirrors the jit's donation
        so the verifier knows how many argument bytes SHOULD alias.
        The wrapper is drop-in: same outputs bit-for-bit, donation
        semantics preserved (the compiled executable consumes donated
        buffers exactly like the jit would).

        Hot-path cost: one pytree flatten + per-leaf aval hash per call
        (the signature check jax's C++ dispatch does natively) and NO
        lock — the cache is a wrapper-local dict (reads GIL-atomic,
        writes under the inventory lock in ``_compile``) and the call
        counter is a benign GIL-racy int (advisory: a lost increment
        shifts a sample, never a result). The cache being PER WRAPPER
        is also the correctness boundary: two builders can share an
        inventory name with the same avals yet close over different
        configs — any shared aval-keyed cache would hand one the
        other's executable (regression-tested)."""
        import inspect
        import weakref

        import jax

        statics = tuple(static_argnames)
        donate = tuple(donate_argnums)
        cache = _WrapperCache()
        rec_box: list = []  # [_FnRecord], refreshed by each compile
        try:
            # canonical call binding: jit's own cache treats f(x),
            # f(x, seed_default) and f(x, k=<declared default>) as ONE
            # entry — without the same normalization, an omitted
            # default vs its explicit spelling would double-compile and
            # tick a spurious recompile (breaking the zero-post-warmup
            # contract on a healthy run)
            call_sig = inspect.signature(fn)
        except (TypeError, ValueError):
            call_sig = None
        with self._lock:
            # prune dead wrappers' refs while registering (reset() is
            # a test hook — production must not grow this unbounded)
            self._cache_refs = [
                r for r in self._cache_refs if r() is not None
            ]
            self._cache_refs.append(weakref.ref(cache))

        def wrapper(*args, **kwargs):
            try:
                split = None
                if call_sig is not None:
                    split = _canonical_call(call_sig, statics, args, kwargs)
                if split is not None:
                    dyn_args, dyn_kwargs, static_vals = split
                    # lower with statics spelled as KEYWORDS: the
                    # Compiled then expects only the dynamic args at
                    # call time, independent of how the caller spelled
                    # (or omitted) the statics
                    lower_args, lower_kwargs = dyn_args, {
                        **dyn_kwargs, **dict(static_vals)
                    }
                else:
                    # no usable signature: original call shape, statics
                    # recognized as keywords only (every in-repo wrap
                    # point passes them that way)
                    dyn_args = args
                    dyn_kwargs = {
                        k: v for k, v in kwargs.items() if k not in statics
                    }
                    static_vals = tuple(
                        (k, kwargs[k]) for k in statics if k in kwargs
                    )
                    lower_args, lower_kwargs = args, kwargs
                static_items = tuple(
                    (k, _static_key(v)) for k, v in static_vals
                )
                sig = []
                for leaf in jax.tree_util.tree_leaves((dyn_args, dyn_kwargs)):
                    if isinstance(leaf, jax.core.Tracer):
                        # inlined inside an enclosing trace: the
                        # enclosing jit owns the compile — pass through
                        return fn(*args, **kwargs)
                    sig.append(_leaf_sig(leaf))
                treedef = jax.tree_util.tree_structure((dyn_args, dyn_kwargs))
                key = (treedef, tuple(sig), static_items)
            except Exception:
                self._count_fallback(name)
                return fn(*args, **kwargs)

            compiled = cache.get(key)
            if compiled is None:
                compiled = self._compile(
                    name, cache, rec_box, key, fn, lower_args, lower_kwargs,
                    donate,
                )
                if compiled is None:  # lowering failed: plain jit path
                    self._count_fallback(name)
                    return fn(*args, **kwargs)

            rec_sample = False
            if rec_box:
                rec = rec_box[0]
                rec.calls += 1  # benign GIL race: advisory counter
                se = self._sample_every
                rec_sample = se > 0 and rec.calls % se == 0
            try:
                if rec_sample:
                    t0 = time.perf_counter()
                    out = compiled(*dyn_args, **dyn_kwargs)
                    jax.block_until_ready(out)
                    self._observe_timing(name, time.perf_counter() - t0)
                    return out
                return compiled(*dyn_args, **dyn_kwargs)
            except Exception:
                # sharding/layout the lowering was not specialized for,
                # or a donated buffer already consumed: the plain jit
                # path owns every edge case
                self._count_fallback(name)
                return fn(*args, **kwargs)

        wrapper.__name__ = f"instrumented_{name}"
        wrapper.__qualname__ = wrapper.__name__
        wrapper.__doc__ = fn.__doc__
        wrapper.__wrapped__ = fn
        return wrapper

    # -- internals --------------------------------------------------------

    def _compile(self, name, cache, rec_box, key, fn, args, kwargs, donate):
        import jax

        try:
            # compiles run outside any lock and capture NO warnings
            # state: warnings.catch_warnings mutates process-global
            # filters and is not thread-safe, so two concurrent
            # compiles could cross-attribute the donation warning —
            # the alias-bytes comparison below is the deterministic
            # signal and subsumes it (an unusable donation aliases
            # fewer bytes than were donated)
            compiled = fn.lower(*args, **kwargs).compile()
        except Exception:
            return None
        cost = _cost_dict(compiled)
        memory = _memory_dict(compiled)
        donated_bytes = 0
        for i in donate:
            if i < len(args):
                for leaf in jax.tree_util.tree_leaves(args[i]):
                    try:
                        aval = jax.api_util.shaped_abstractify(leaf)
                        donated_bytes += int(
                            aval.size * aval.dtype.itemsize
                        )
                    except Exception:
                        pass
        alias = (memory or {}).get("alias_bytes", 0)
        fallback = donated_bytes > 0 and alias < donated_bytes
        tel = _device_tel()
        with self._lock:
            if key in cache:
                return cache[key]  # racing compile: theirs won
            cache[key] = compiled
            rec = self._records.get(name)
            if rec is None:
                rec = self._records[name] = _FnRecord(name)
            rec_box[:] = [rec]  # refresh: reset() may have swapped it
            rec.compiles += 1
            recompile = rec.compiles > 1
            if recompile:
                rec.recompiles += 1
            if fallback:
                rec.donation_fallbacks += 1
            rec.cost = cost
            rec.memory = memory
            rec.donated_bytes = donated_bytes
        if tel is not None:
            tel["compiles"].labels(fn=name).inc()
            if recompile:
                tel["recompiles"].labels(fn=name).inc()
            if fallback:
                tel["donation_fallbacks"].labels(fn=name).inc()
        return compiled

    def _count_fallback(self, name: str) -> None:
        with self._lock:
            rec = self._records.get(name)
            if rec is None:
                rec = self._records[name] = _FnRecord(name)
            rec.dispatch_fallbacks += 1
        tel = _device_tel()
        if tel is not None:
            tel["dispatch_fallbacks"].labels(fn=name).inc()

    def _observe_timing(self, name: str, wall_s: float) -> None:
        """Fold one timed dispatch into the function's roofline view
        and the live gauges. Achieved rates derive from the latest
        compile's cost analysis; fracs only exist when the peak tables
        know this device kind."""
        import jax

        with self._lock:
            rec = self._records.get(name)
            cost = dict(rec.cost) if rec and rec.cost else None
        if cost is None or wall_s <= 0:
            return
        try:
            kind = jax.devices()[0].device_kind
        except Exception:
            kind = "?"
        hbm_peak, flops_peak = _peaks(kind)
        timing: Dict[str, Any] = {"wall_ms": round(wall_s * 1e3, 4)}
        tel = _device_tel()
        gb_s = tflops = None
        if cost.get("bytes_accessed"):
            gb_s = cost["bytes_accessed"] / wall_s / 1e9
            timing["achieved_gb_s"] = round(gb_s, 3)
        if cost.get("flops"):
            tflops = cost["flops"] / wall_s / 1e12
            timing["achieved_tflops"] = round(tflops, 5)
        if hbm_peak and gb_s is not None:
            timing["frac_of_hbm_peak"] = round(gb_s / hbm_peak, 5)
        if flops_peak and tflops is not None:
            timing["mfu"] = round(tflops / flops_peak, 6)
        with self._lock:
            if rec is not None:
                rec.last_timing = timing
        if tel is not None:
            if gb_s is not None:
                tel["kernel_gb_s"].labels(fn=name).set(gb_s)
            if tflops is not None:
                tel["kernel_tflops"].labels(fn=name).set(tflops)
            if "frac_of_hbm_peak" in timing:
                tel["roofline_frac"].labels(fn=name, resource="hbm").set(
                    timing["frac_of_hbm_peak"]
                )
            if "mfu" in timing:
                tel["roofline_frac"].labels(fn=name, resource="flops").set(
                    timing["mfu"]
                )

    # -- reads ------------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The record-embeddable inventory view: per-function compile /
        recompile / donation-fallback counts with the latest cost and
        memory analyses, plus the post-warmup recompile total (zero on
        a healthy steady-state run)."""
        with self._lock:
            fns = {
                name: rec.as_dict()
                for name, rec in sorted(self._records.items())
            }
            marks = dict(self._warmup_marks)
            recs = dict(self._records)
        post_warmup = 0
        for name, rec in recs.items():
            c0, r0 = marks.get(name, (0, 0))
            # a function first compiled AFTER the mark is warmup debt
            # too: steady state means no new programs at all
            post_warmup += (rec.compiles - c0) if name in marks else (
                rec.compiles
            )
            # avoid double counting: recompiles are included in
            # compiles growth above
        out: Dict[str, Any] = {
            "functions": fns,
            "recompiles_post_warmup": post_warmup if marks else None,
            "donation_fallbacks_total": sum(
                rec.donation_fallbacks for rec in recs.values()
            ),
        }
        return out


class HbmMonitor:
    """Registry collector for device-memory truth.

    ``collect()`` runs before every snapshot/render (the registry
    collector contract): per-device ``memory_stats()`` where the
    backend provides them (TPU: bytes_in_use / peak_bytes_in_use /
    bytes_limit) and the cross-backend live-buffer total from
    ``jax.live_arrays()`` with a process-lifetime high-water mark — so
    a CPU-container test run still exercises the same family the chip
    capture reads. The owner must keep a strong reference (collectors
    are weakrefs); :func:`install_hbm_monitor` parks it module-side.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._live_high_water = 0     # guarded-by: _lock
        self._last: Dict[str, Any] = {}  # guarded-by: _lock

    def collect(self) -> None:
        import jax

        tel = _device_tel()
        live_bytes = 0
        try:
            for arr in jax.live_arrays():
                live_bytes += int(getattr(arr, "nbytes", 0) or 0)
        except Exception:
            live_bytes = 0
        devices: Dict[str, Dict[str, int]] = {}
        try:
            for d in jax.local_devices():
                try:
                    ms = d.memory_stats()
                except Exception:
                    ms = None
                if not ms:
                    continue
                label = f"{d.platform}:{d.id}"
                stats = {
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0)),
                    "bytes_limit": int(ms.get("bytes_limit", 0)),
                }
                devices[label] = stats
        except Exception:
            pass
        with self._lock:
            self._live_high_water = max(self._live_high_water, live_bytes)
            high = self._live_high_water
            self._last = {
                "live_buffer_bytes": live_bytes,
                "live_buffer_high_water_bytes": high,
                "devices": devices,
            }
        if tel is None:
            return
        tel["live_buffers"].set(live_bytes)
        tel["live_high_water"].set(high)
        for label, stats in devices.items():
            tel["hbm_bytes_in_use"].labels(device=label).set(
                stats["bytes_in_use"]
            )
            tel["hbm_high_water"].labels(device=label).set(
                stats["peak_bytes_in_use"]
            )
            tel["hbm_limit"].labels(device=label).set(stats["bytes_limit"])
            if stats["bytes_limit"]:
                tel["hbm_frac_used"].labels(device=label).set(
                    stats["bytes_in_use"] / stats["bytes_limit"]
                )

    def snapshot(self) -> Dict[str, Any]:
        """Freshly collected HBM view for the bench record."""
        self.collect()
        with self._lock:
            return dict(self._last)


# -- module-level plumbing (the process-default inventory) -----------------

_default_inventory = DeviceInventory()
_hbm_monitor: Optional[HbmMonitor] = None
_hbm_lock = threading.Lock()


def inventory() -> DeviceInventory:
    return _default_inventory


def instrument(
    name: str,
    fn,
    static_argnames: Sequence[str] = (),
    donate_argnums: Sequence[int] = (),
):
    """``DeviceInventory.instrument`` against the process inventory —
    the one-liner for module-level wrap points (ops/kv_ops, the step
    builders)."""
    return _default_inventory.instrument(
        name, fn, static_argnames=static_argnames,
        donate_argnums=donate_argnums,
    )


def set_sampling(every: int) -> int:
    return _default_inventory.set_sampling(every)


def mark_warmup() -> None:
    _default_inventory.mark_warmup()


def reset() -> None:
    """Test hook: clear the process inventory (compiled cache included)."""
    _default_inventory.reset()


def hbm_monitor() -> HbmMonitor:
    """The process HbmMonitor (created on first use; NOT yet registered
    as a collector — see :func:`install_hbm_monitor`)."""
    global _hbm_monitor
    with _hbm_lock:
        if _hbm_monitor is None:
            _hbm_monitor = HbmMonitor()
        return _hbm_monitor


def install_hbm_monitor(reg=None) -> Optional[HbmMonitor]:
    """Register the HBM collector on ``reg`` (default registry when
    None) so every snapshot/scrape carries fresh ``ps_device_hbm_*`` /
    live-buffer gauges. Idempotent per registry (re-adding a weakref'd
    bound method is harmless but avoided). No-op returning None while
    telemetry is disabled."""
    if reg is None:
        if not telemetry_registry.enabled():
            return None
        reg = telemetry_registry.default_registry()
    mon = hbm_monitor()
    installed = getattr(reg, "_ps_device_hbm_installed", False)
    if not installed:
        reg.add_collector(mon.collect)
        try:
            reg._ps_device_hbm_installed = True
        except Exception:
            pass
    return mon


def snapshot() -> Dict[str, Any]:
    """The bench record's ``device`` section body: inventory counters +
    cost analyses + the HBM view, stamped with the backend identity."""
    out = _default_inventory.snapshot()
    try:
        import jax

        dev = jax.devices()[0]
        out["backend"] = jax.default_backend()
        out["device_kind"] = dev.device_kind
        hbm_peak, flops_peak = _peaks(dev.device_kind)
        out["hbm_peak_gb_s"] = hbm_peak
        out["flops_peak_tflops"] = flops_peak
    except Exception:
        pass
    out["hbm"] = hbm_monitor().snapshot()
    return out
