"""Merged cross-thread timeline: JSONL span events → Perfetto-openable
Chrome trace JSON with flow arrows.

The span sink (:mod:`telemetry.spans`) appends flat per-thread events;
the pipeline built since PR 1 is a multi-thread dataflow (feeder →
countmin filter → prep pool → DeviceUploader → trainer step → executor
run; serve submit → admission → coalescer flush → executor → reply)
whose bottleneck shifts per run. This module turns the flat stream into
a *timeline*: per-thread tracks, flow arrows stitching each batch or
request across threads (the ``flow`` ids :func:`spans.new_flow`
allocates), and the input of the critical-path analyzer
(:mod:`telemetry.attribution`).

Export format is the Chrome trace-event JSON array form — open it at
https://ui.perfetto.dev (or chrome://tracing): each span becomes one
``"ph": "X"`` complete event on its thread's track, consecutive spans
of the same flow on *different* threads are joined by ``"s"``/``"f"``
flow arrows, and ``abandoned`` terminators render as zero-duration
instant events so a worker-exception tombstone is visible exactly where
the batch died. ``doc/OBSERVABILITY.md`` ("Reading a timeline") walks
a rendered example.

On-TPU runs can interleave device-side context: wrap launches in
:func:`device_annotation` and capture a ``jax.profiler`` trace beside
the host timeline (``bench.py --profile``) — the annotation names show
up inside the profiler's device tracks, keyed by the same step names.
"""

from __future__ import annotations

import contextlib
import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from . import spans as _spans

# re-exported so call sites can treat timeline as the one flow API
new_flow = _spans.new_flow
flow_scope = _spans.flow_scope
current_flow = _spans.current_flow


def load_events(path: str) -> List[Dict[str, Any]]:
    """Read a JSONL span trace, skipping half-written trailing lines
    (a killed run must still be analyzable)."""
    events: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict):
                events.append(ev)
    return events


def _start_end(ev: Dict[str, Any]) -> Tuple[float, float]:
    t0 = float(ev.get("t_wall", 0.0))
    dur = ev.get("dur_s")
    if dur is None and "total_s" in ev:
        # executor.step stamps t_wall at FINISH and total_s spans
        # submit→finish (system/executor.py) — render the full interval
        # so the step is a box, not a zero-width sliver at its end
        return t0 - float(ev["total_s"]), t0
    return t0, t0 + float(dur or 0.0)


def events_window(events: Iterable[Dict[str, Any]]) -> Tuple[float, float]:
    """(earliest start, latest end) wall time across ``events``."""
    starts, ends = [], []
    for ev in events:
        s, e = _start_end(ev)
        starts.append(s)
        ends.append(e)
    if not starts:
        return 0.0, 0.0
    return min(starts), max(ends)


def flows(events: Iterable[Dict[str, Any]]) -> Dict[int, List[Dict[str, Any]]]:
    """Events grouped by flow id, each group sorted by start time.
    Events without a flow are omitted (they still render on their
    thread track; they just draw no arrows)."""
    out: Dict[int, List[Dict[str, Any]]] = {}
    for ev in events:
        fid = ev.get("flow")
        if fid is None:
            continue
        out.setdefault(int(fid), []).append(ev)
    for seq in out.values():
        seq.sort(key=lambda e: _start_end(e)[0])
    return out


def merge_node_events(
    events_by_node: Dict[str, Sequence[Dict[str, Any]]],
    offsets: Optional[Dict[str, float]] = None,
) -> List[Dict[str, Any]]:
    """Merge several nodes' span streams into ONE timeline.

    Three things make per-node streams unmergeable raw, and this fixes
    each: (1) wall clocks differ across hosts — ``offsets[node]``
    (seconds to ADD to that node's clock, the
    ``system/heartbeat.ClockSync`` convention) aligns every event onto
    the caller's clock; (2) thread names collide ("MainThread" on every
    node) — threads are tagged ``node/thread`` and the event gains a
    ``node`` field (the Chrome export renders one process track per
    node); (3) flow ids are per-process counters, so two nodes' local
    flow 7 are different units — flows are renumbered by
    ``(origin node, id)``, where the origin is the event's
    ``flow_node`` (a flow that crossed the Van keeps its origin, which
    is exactly how the sending span and the receiving executor land on
    the SAME merged flow and draw the cross-node arrow).

    Inputs are unmodified; returns a new time-sorted list.
    """
    offsets = offsets or {}
    flow_map: Dict[Tuple[str, int], int] = {}

    def global_flow(origin: str, fid: Any) -> int:
        key = (origin, int(fid))
        if key not in flow_map:
            flow_map[key] = len(flow_map) + 1
        return flow_map[key]

    merged: List[Dict[str, Any]] = []
    for node in sorted(events_by_node):
        off = float(offsets.get(node, 0.0))
        for ev in events_by_node[node]:
            ev = dict(ev)
            ev["node"] = node
            if "t_wall" in ev:
                ev["t_wall"] = float(ev["t_wall"]) + off
            ev["thread"] = f"{node}/{ev.get('thread', '?')}"
            origin = str(ev.pop("flow_node", None) or node)
            if ev.get("flow") is not None:
                ev["flow"] = global_flow(origin, ev["flow"])
            if isinstance(ev.get("flows"), (list, tuple)):
                ev["flows"] = [
                    global_flow(origin, f) for f in ev["flows"]
                ]
            merged.append(ev)
    merged.sort(key=lambda e: _start_end(e)[0])
    return merged


def merge_node_sinks(
    node_paths: Dict[str, str],
    offsets: Optional[Dict[str, float]] = None,
) -> List[Dict[str, Any]]:
    """:func:`merge_node_events` over per-node JSONL sink files."""
    return merge_node_events(
        {node: load_events(path) for node, path in node_paths.items()},
        offsets,
    )


def merge_device_track(
    host_events: Sequence[Dict[str, Any]],
    device_events: Sequence[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Merge a profiler-derived device track
    (utils/profiling.device_track_events) into a host span timeline.

    Each device span inherits the flow id of the ``executor.step`` span
    whose submit→finish interval contains its midpoint — the submitting
    step — so the Chrome export draws flow arrows from the host step
    phases onto the device ops they launched, and the attribution
    reader can group device time per unit of work. Device spans whose
    midpoint lands in no step (profiler warmup, gaps) merge without a
    flow: they still render on the ``device:<pid>`` track, they just
    draw no arrows. Returns a new time-sorted list; inputs unmodified.
    """
    steps: List[Tuple[float, float, int]] = []
    for ev in host_events:
        if ev.get("name") == "executor.step" and ev.get("flow") is not None:
            s, e = _start_end(ev)
            steps.append((s, e, int(ev["flow"])))
    steps.sort()
    out = list(host_events)
    for ev in device_events:
        ev = dict(ev)
        mid = float(ev.get("t_wall", 0.0)) + float(ev.get("dur_s", 0.0)) / 2.0
        for s, e, fid in steps:
            if s <= mid <= e:
                ev["flow"] = fid
                break
        out.append(ev)
    out.sort(key=lambda e: _start_end(e)[0])
    return out


def to_chrome_trace(
    events: Sequence[Dict[str, Any]],
    *,
    pid: int = 1,
    process_name: str = "parameter_server_tpu",
) -> Dict[str, Any]:
    """Render span events as a Chrome trace-event JSON object.

    Deterministic for a given event list: thread track ids are assigned
    in first-appearance order, timestamps are microseconds relative to
    the earliest event (Perfetto prefers small offsets over epoch
    micros). Flow arrows connect consecutive spans of one flow id
    across thread boundaries; a coalescer flush span that carries a
    ``flows`` list additionally receives one arrow from each merged
    request's preceding span (fan-in). ``abandoned`` events render as
    instant (``"ph": "i"``) tombstones.

    Node-tagged events (:func:`merge_node_events` sets ``ev["node"]``)
    render as one Perfetto *process* per node (``process_name:
    <name>:<node>``) — single-node traces keep the legacy single-pid
    shape bit-for-bit. Flow arrows cross process tracks the same way
    they cross threads, which is how a flow's Van hop draws as an arrow
    from the sending node's span to the receiving node's executor step.
    """
    t_base, _ = events_window(events)
    pids: Dict[Any, int] = {}
    tids: Dict[str, Tuple[int, int]] = {}  # thread -> (pid, tid)
    trace: List[Dict[str, Any]] = []

    def pid_of(node) -> int:
        if node not in pids:
            pids[node] = pid + len(pids)
            name = process_name if node is None else f"{process_name}:{node}"
            trace.append(
                {
                    "ph": "M",
                    "pid": pids[node],
                    "tid": 0,
                    "name": "process_name",
                    "args": {"name": name},
                }
            )
        return pids[node]

    def track_of(ev: Dict[str, Any]) -> Tuple[int, int]:
        thread = str(ev.get("thread", "?"))
        if thread not in tids:
            p = pid_of(ev.get("node"))
            tids[thread] = (p, len(tids) + 1)
            trace.append(
                {
                    "ph": "M",
                    "pid": p,
                    "tid": tids[thread][1],
                    "name": "thread_name",
                    "args": {"name": thread},
                }
            )
        return tids[thread]

    def us(t_wall: float) -> float:
        return round((t_wall - t_base) * 1e6, 3)

    meta_keys = ("kind", "name", "t_wall", "dur_s", "thread")
    for ev in events:
        epid, tid = track_of(ev)
        start, end = _start_end(ev)
        args = {k: v for k, v in ev.items() if k not in meta_keys}
        if ev.get("abandoned"):
            trace.append(
                {
                    "ph": "i",
                    "pid": epid,
                    "tid": tid,
                    "name": str(ev.get("name", "span")) + " (abandoned)",
                    "ts": us(start),
                    "s": "t",  # thread-scoped instant marker
                    "args": args,
                }
            )
            continue
        trace.append(
            {
                "ph": "X",
                "pid": epid,
                "tid": tid,
                "name": str(ev.get("name", "span")),
                "ts": us(start),
                "dur": round((end - start) * 1e6, 3),
                "args": args,
            }
        )

    # flow arrows: consecutive spans of one flow id on different threads
    arrows: List[Dict[str, Any]] = []
    by_flow = flows(events)
    for fid, seq in sorted(by_flow.items()):
        for prev, nxt in zip(seq, seq[1:]):
            if prev.get("thread") == nxt.get("thread"):
                continue  # same track: adjacency already reads left-to-right
            _, prev_end = _start_end(prev)
            nxt_start, _ = _start_end(nxt)
            s_pid, s_tid = track_of(prev)
            f_pid, f_tid = track_of(nxt)
            arrows.append(
                {
                    "ph": "s",
                    "pid": s_pid,
                    "tid": s_tid,
                    "name": "flow",
                    "cat": "flow",
                    "id": fid,
                    "ts": us(prev_end),
                }
            )
            arrows.append(
                {
                    "ph": "f",
                    "bp": "e",
                    "pid": f_pid,
                    "tid": f_tid,
                    "name": "flow",
                    "cat": "flow",
                    "id": fid,
                    "ts": us(max(nxt_start, prev_end)),
                }
            )
    # fan-in arrows: a flush/merge span naming the flows it absorbed
    for ev in events:
        merged = ev.get("flows")
        if not isinstance(merged, (list, tuple)) or ev.get("flow") is None:
            continue
        start, _ = _start_end(ev)
        e_pid, e_tid = track_of(ev)
        for fid in merged:
            seq = by_flow.get(int(fid))
            if not seq:
                continue
            # the arrow originates from the merged request's span
            # PRECEDING the flush — not the flow's last span overall,
            # which (serve.reply) can postdate the flush and would draw
            # backwards causality. Clamp the origin into the preceding
            # span's interval when it is still open at flush start.
            preceding = [e for e in seq if _start_end(e)[0] <= start]
            if not preceding:
                continue
            prev = preceding[-1]
            _, prev_end = _start_end(prev)
            s_pid, s_tid = track_of(prev)
            arrows.append(
                {
                    "ph": "s",
                    "pid": s_pid,
                    "tid": s_tid,
                    "name": "flow",
                    "cat": "flow",
                    "id": int(fid),
                    "ts": us(min(prev_end, start)),
                }
            )
            arrows.append(
                {
                    "ph": "f",
                    "bp": "e",
                    "pid": e_pid,
                    "tid": e_tid,
                    "name": "flow",
                    "cat": "flow",
                    "id": int(fid),
                    "ts": us(start),
                }
            )
    trace.extend(arrows)
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def export_chrome_trace(
    jsonl_path: str, out_path: str, **kwargs
) -> Dict[str, Any]:
    """Load a JSONL span trace and write the Chrome trace JSON next to
    it; returns the trace object (callers embed summary stats). A
    merged device track in the JSONL (``device.*`` events from a
    --profile run) is re-stitched to its submitting steps so the export
    carries the host→device flow arrows."""
    from .attribution import is_device_event

    events = load_events(jsonl_path)
    dev = [e for e in events if is_device_event(e)]
    if dev:
        events = merge_device_track(
            [e for e in events if not is_device_event(e)], dev
        )
    trace = to_chrome_trace(events, **kwargs)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    return trace


def device_annotation(name: str):
    """Optional ``jax.profiler`` device-side annotation: inside a
    profiler capture on TPU, names the enclosed launches so the device
    trace's tracks line up with the host timeline's step names. Returns
    a null context when jax (or the profiler) is unavailable — safe to
    use unconditionally."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:
        return contextlib.nullcontext()
