"""The seventh plane is time: multi-resolution telemetry history.

Six planes (metrics, timeline, cluster, device, flight-recorder,
learning) answer "what is true NOW at one scrape". This module is the
retention layer behind them: an embedded, bounded, RRD-style ring
cascade (default 1s x 10m -> 10s x 2h -> 60s x 12h) fed by a registry
collector hook, so every fold of the live registry lands one typed
sample in every resolution level simultaneously.

Typed downsampling per instrument kind (doc/OBSERVABILITY.md "History
plane"):

- **counters -> rates**: each ring cell holds the counter's INCREASE
  within the cell (reset-aware: a restart contributes the post-reset
  value, never a negative delta), so per-second rates are computable at
  every resolution by ``delta / cell_width``;
- **gauges -> last/min/max**: each cell keeps the last sample plus the
  cell's min/max envelope — a spike inside a 60s cell stays visible;
- **histograms -> bucket-delta merges**: each cell holds the
  element-wise bucket-count delta (+ count/sum deltas), so windowed
  quantiles stay computable at every resolution by summing cell deltas
  and interpolating over the declared bounds.

Cardinality is capped per metric family and in total; a series past
the cap is DROPPED (once, loudly: ``ps_history_dropped_series_total``)
rather than allowed to grow the rings without bound — history can
never OOM a node.

Consumers: alert multi-window burn rates and ``trend`` drift rules
(telemetry/alerts.py) evaluate from these rings; per-node rings ride
the aux report plane into the ClusterAggregator; ``/metrics/history``
serves range queries; flight-recorder bundles embed the down-sampled
hour before their trigger (telemetry/blackbox.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import registry as telemetry_registry

#: default ring cascade: (cell width seconds, slots) per level — about
#: 10 minutes at 1s, 2 hours at 10s, 12 hours at 60s
DEFAULT_RESOLUTIONS: Tuple[Tuple[float, int], ...] = (
    (1.0, 600),
    (10.0, 720),
    (60.0, 720),
)

#: default per-metric / process-wide series caps (the escape valve)
MAX_SERIES_PER_METRIC = 32
MAX_SERIES_TOTAL = 1024


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _labels_match(series_labels: Dict[str, str],
                  want: Optional[Dict[str, str]]) -> bool:
    """Subset match: ``want=None`` matches everything; otherwise every
    given pair must be present in the series' labels."""
    if want is None:
        return True
    return all(str(series_labels.get(k)) == str(v) for k, v in want.items())


class _Level:
    """One resolution level of one series: parallel rings indexed by
    ``epoch % slots`` with the owning epoch stored per cell, so stale
    cells (lapped by the ring) are recognized at read time instead of
    being zeroed eagerly."""

    __slots__ = ("res", "slots", "epochs", "a", "b", "c", "h")

    def __init__(self, res: float, slots: int, kind: str, nbuckets: int):
        self.res = res
        self.slots = slots
        # None = never claimed (an int sentinel like -1 would collide
        # with a real epoch under near-zero fake clocks)
        self.epochs: List[Optional[int]] = [None] * slots
        # typed payload rings:
        #   counter:   a = delta
        #   gauge:     a = last, b = min, c = max
        #   histogram: a = count delta, b = sum delta, h = bucket deltas
        self.a = [0.0] * slots
        self.b = [0.0] * slots if kind in ("gauge", "histogram") else None
        self.c = [0.0] * slots if kind == "gauge" else None
        self.h: Optional[List[Optional[List[int]]]] = (
            [None] * slots if kind == "histogram" else None
        )


class _Series:
    """One tracked (metric, label-set): the cumulative baseline used
    for delta computation plus one ring set per resolution level."""

    __slots__ = ("name", "kind", "labels", "bounds", "levels",
                 "prev_value", "prev_buckets", "prev_count", "prev_sum")

    def __init__(self, name: str, kind: str, labels: Dict[str, str],
                 bounds: Optional[List[float]],
                 resolutions: Sequence[Tuple[float, int]]):
        self.name = name
        self.kind = kind
        self.labels = dict(labels)
        self.bounds = list(bounds) if bounds is not None else None
        nb = len(self.bounds) if self.bounds is not None else 0
        self.levels = [
            _Level(res, slots, kind, nb) for res, slots in resolutions
        ]
        self.prev_value: Optional[float] = None
        self.prev_buckets: Optional[List[int]] = None
        self.prev_count = 0
        self.prev_sum = 0.0


def percentile_from_buckets(
    bounds: Sequence[float], dcounts: Sequence[float], dcount: float, q: float
) -> Optional[float]:
    """Windowed percentile from merged bucket-count deltas — the same
    bucket-edge interpolation as alerts.windowed_quantile, kept here so
    every history resolution answers quantile queries identically."""
    if dcount <= 0:
        return None
    rank = q * dcount
    cum = 0.0
    for i, c in enumerate(dcounts):
        if c <= 0:
            continue
        lo = bounds[i - 1] if i else 0.0
        if cum + c >= rank:
            frac = (rank - cum) / c
            return lo + frac * (bounds[i] - lo)
        cum += c
    return float(bounds[-1])


def theil_sen(points: Sequence[Tuple[float, float]]) -> Optional[float]:
    """Median of pairwise slopes — the robust slope estimator behind
    the ``trend`` alert kind (a single outlier cell cannot fake or
    hide a drift the way it skews a least-squares fit). O(n^2) pairs;
    callers bound n by the queried window / resolution."""
    slopes: List[float] = []
    n = len(points)
    for i in range(n):
        t0, v0 = points[i]
        for j in range(i + 1, n):
            t1, v1 = points[j]
            if t1 > t0:
                slopes.append((v1 - v0) / (t1 - t0))
    if not slopes:
        return None
    slopes.sort()
    m = len(slopes)
    mid = m // 2
    return slopes[mid] if m % 2 else 0.5 * (slopes[mid - 1] + slopes[mid])


def monotonic_fractions(values: Sequence[float]) -> Tuple[float, float]:
    """(frac_up, frac_down) over consecutive deltas — the concordance
    gate that separates a sustained ramp from noise around a level."""
    ups = downs = 0
    for a, b in zip(values, values[1:]):
        if b > a:
            ups += 1
        elif b < a:
            downs += 1
    steps = max(1, len(values) - 1)
    return ups / steps, downs / steps


def drift_check(
    samples: Sequence[Tuple[float, float]],
    baseline_frac: float = 0.3,
    tail_frac: float = 0.3,
    tol: float = 0.15,
    min_points: int = 6,
) -> dict:
    """Live steady-state drift verdict over a run's own (t, throughput)
    windows — bench_diff's idea reborn online: the tail of the run is
    judged against its post-warmup baseline, same-host same-run, so no
    cross-run capacity drift can alibi or fake the verdict. Median of
    each segment (robust to one throttled window) + the Theil-Sen
    slope as supporting evidence. ``drifting`` only flags DOWNWARD
    drift beyond ``tol`` — a run that speeds up is not a defect."""
    pts = [(float(t), float(v)) for t, v in samples]
    out: dict = {"n": len(pts), "tol": tol}
    if len(pts) < min_points:
        out["verdict"] = "insufficient-data"
        out["drifting"] = False
        return out
    pts.sort(key=lambda p: p[0])
    k_base = max(2, int(len(pts) * baseline_frac))
    k_tail = max(2, int(len(pts) * tail_frac))

    def median(vals: List[float]) -> float:
        s = sorted(vals)
        m = len(s) // 2
        return s[m] if len(s) % 2 else 0.5 * (s[m - 1] + s[m])

    base = median([v for _, v in pts[:k_base]])
    tail = median([v for _, v in pts[-k_tail:]])
    ratio = tail / base if base > 0 else None
    out.update({
        "baseline_median": base,
        "tail_median": tail,
        "ratio": ratio,
        "slope_per_s": theil_sen(pts),
    })
    drifting = ratio is not None and ratio < 1.0 - tol
    out["drifting"] = drifting
    out["verdict"] = "drift-down" if drifting else "ok"
    return out


class HistoryStore:
    """The bounded multi-resolution store over one MetricsRegistry.

    ``install()`` registers :meth:`collect` as a registry collector, so
    every snapshot/export/render keeps the rings fresh; the aux loop
    and the alert evaluator also fold explicitly (fake-clock tests
    drive :meth:`fold` with explicit timestamps). Folding is floored at
    half the base resolution — a tight scrape loop cannot multiply the
    fold cost.
    """

    def __init__(
        self,
        registry: Optional[telemetry_registry.MetricsRegistry] = None,
        resolutions: Sequence[Tuple[float, int]] = DEFAULT_RESOLUTIONS,
        max_series_per_metric: int = MAX_SERIES_PER_METRIC,
        max_series_total: int = MAX_SERIES_TOTAL,
        clock: Callable[[], float] = time.time,
    ):
        res = sorted(
            (float(r), int(s)) for r, s in resolutions
        )
        if not res or any(r <= 0 or s <= 1 for r, s in res):
            raise ValueError(f"bad resolutions {resolutions!r}")
        self.registry = registry or telemetry_registry.default_registry()
        self.resolutions: Tuple[Tuple[float, int], ...] = tuple(res)
        self.max_series_per_metric = int(max_series_per_metric)
        self.max_series_total = int(max_series_total)
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, tuple], _Series] = {}  # guarded-by: _lock
        self._per_metric: Dict[str, int] = {}  # guarded-by: _lock
        self._dropped: set = set()  # guarded-by: _lock
        self._last_fold = -float("inf")  # guarded-by: _lock
        self._folds = 0  # guarded-by: _lock
        self._tel = None
        if telemetry_registry.enabled():
            from .instruments import history_instruments

            self._tel = history_instruments(self.registry)

    # -- feed --

    def install(self) -> "HistoryStore":
        """Hook :meth:`collect` into the registry's collector list (the
        bound method is weakly referenced — keep the store alive)."""
        self.registry.add_collector(self.collect)
        return self

    def collect(self) -> None:
        """Registry collector hook: rate-limited fold at wall time."""
        self.fold()

    def fold(self, now: Optional[float] = None, force: bool = False) -> bool:
        """Fold the registry's current state into every ring level;
        returns whether a fold ran (floored at half the base
        resolution unless ``force``)."""
        now = self._clock() if now is None else float(now)
        base_res = self.resolutions[0][0]
        with self._lock:
            if not force and now - self._last_fold < 0.5 * base_res:
                return False
            prev = self._last_fold
            self._last_fold = now
        # attribute this fold's deltas to the MIDPOINT of the fold
        # interval (clamped to one base cell back): a fold landing
        # exactly on a cell boundary would otherwise write the previous
        # second's accrual into a cell with ~zero elapsed width, and
        # that cell's per-point rate would explode
        if prev == -float("inf"):
            t_attr = now
        else:
            t_attr = max((prev + now) / 2.0, now - base_res)
        t0 = time.perf_counter()
        # read WITHOUT running collectors: fold() is itself invoked as
        # one (registry.collect would recurse), and the snapshot paths
        # that want flushed producers already ran them before this hook
        export = self.registry.export_state(collect=False)
        with self._lock:
            for name in export:
                decl = export[name]
                kind = decl["type"]
                if kind not in ("counter", "gauge", "histogram"):
                    continue
                bounds = decl.get("buckets")
                for s in decl["series"]:
                    self._fold_series_locked(name, kind, bounds, s, t_attr)
            self._folds += 1
            nseries = len(self._series)
        fold_s = time.perf_counter() - t0
        if self._tel is not None:
            self._tel["fold_seconds"].observe(fold_s)
            self._tel["folds"].inc()
            self._tel["series"].set(nseries)
            last_collect = getattr(self.registry, "last_collect_s", None)
            if last_collect is not None:
                self._tel["collect_seconds"].set(last_collect)
        return True

    def _fold_series_locked(  # holds-lock: _lock (fold's export walk)
        self, name: str, kind: str, bounds, s: dict, now: float
    ) -> None:
        key = (name, _series_key(s["labels"]))
        ser = self._series.get(key)
        if ser is None:
            per = self._per_metric.get(name, 0)
            if (
                per >= self.max_series_per_metric
                or len(self._series) >= self.max_series_total
            ):
                if key not in self._dropped:
                    self._dropped.add(key)
                    if self._tel is not None:
                        self._tel["dropped"].labels(metric=name).inc()
                return
            ser = self._series[key] = _Series(
                name, kind, s["labels"], bounds, self.resolutions
            )
            self._per_metric[name] = per + 1
        if ser.kind != kind:
            return  # re-declared name: keep the original rings honest

        if kind == "counter":
            v = float(s["value"])
            prev = ser.prev_value
            # reset-aware increase (a restarted process contributes its
            # post-reset total, never a negative delta)
            delta = v if prev is None or v < prev else v - prev
            if prev is None:
                delta = 0.0  # first sight: no window to attribute to
            ser.prev_value = v
            for lv in ser.levels:
                idx, fresh = self._cell(lv, now)
                lv.a[idx] = delta if fresh else lv.a[idx] + delta
        elif kind == "gauge":
            v = float(s["value"])
            for lv in ser.levels:
                idx, fresh = self._cell(lv, now)
                lv.a[idx] = v
                if fresh:
                    lv.b[idx] = v
                    lv.c[idx] = v
                else:
                    if v < lv.b[idx]:
                        lv.b[idx] = v
                    if v > lv.c[idx]:
                        lv.c[idx] = v
        else:  # histogram
            cur_b = [int(c) for c in s["buckets"]]
            cur_n, cur_sum = int(s["count"]), float(s["sum"])
            pb = ser.prev_buckets
            if pb is None:
                db, dn, ds = None, 0, 0.0  # first sight: baseline only
            elif cur_n < ser.prev_count or len(pb) != len(cur_b):
                db, dn, ds = cur_b, cur_n, cur_sum  # reset: post-reset obs
            else:
                db = [max(0, a - b) for a, b in zip(cur_b, pb)]
                dn = cur_n - ser.prev_count
                ds = cur_sum - ser.prev_sum
            ser.prev_buckets = cur_b
            ser.prev_count, ser.prev_sum = cur_n, cur_sum
            if db is None or dn <= 0:
                return
            for lv in ser.levels:
                idx, fresh = self._cell(lv, now)
                if fresh or lv.h[idx] is None:
                    lv.h[idx] = list(db)
                    lv.a[idx] = float(dn)
                    lv.b[idx] = ds
                else:
                    cell = lv.h[idx]
                    for i, d in enumerate(db):
                        cell[i] += d
                    lv.a[idx] += float(dn)
                    lv.b[idx] += ds

    @staticmethod
    def _cell(lv: _Level, now: float) -> Tuple[int, bool]:
        """(ring index, is-a-fresh-epoch) for ``now`` at this level —
        claiming a lapped cell resets nothing eagerly; the ``fresh``
        flag tells the caller to overwrite."""
        epoch = int(now // lv.res)
        idx = epoch % lv.slots
        fresh = lv.epochs[idx] != epoch
        if fresh:
            lv.epochs[idx] = epoch
        return idx, fresh

    # -- queries --

    def _pick_level(
        self, ser: _Series, window_s: float, resolution: Optional[float]
    ) -> _Level:
        if resolution is not None:
            for lv in ser.levels:
                if lv.res >= float(resolution) - 1e-9:
                    return lv
            return ser.levels[-1]
        for lv in ser.levels:
            if lv.res * lv.slots >= window_s:
                return lv
        return ser.levels[-1]

    def _cells_in_window_locked(
        self, ser: _Series, lv: _Level, window_s: float, now: float
    ) -> List[Tuple[float, int]]:
        """[(cell start time, ring index)] for live cells inside the
        window, oldest first. The CURRENT (still-open) cell is included
        — rates over it use the elapsed fraction, not the full width."""
        e_now = int(now // lv.res)
        e_min = max(e_now - lv.slots + 1, int((now - window_s) // lv.res))
        out = []
        for epoch in range(e_min, e_now + 1):
            idx = epoch % lv.slots
            if lv.epochs[idx] == epoch:
                out.append((epoch * lv.res, idx))
        return out

    def query(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        window_s: float = 600.0,
        resolution: Optional[float] = None,
        q: Optional[float] = None,
        now: Optional[float] = None,
    ) -> dict:
        """Range query: typed points per matching series. Counters
        yield ``{t, delta, rate}``; gauges ``{t, last, min, max}``;
        histograms ``{t, count, sum, rate}`` plus ``q``'s windowed
        percentile per cell when requested."""
        now = self._clock() if now is None else float(now)
        window_s = float(window_s)
        out = {
            "name": name,
            "window_s": window_s,
            "t": now,
            "series": [],
        }
        with self._lock:
            matches = [
                ser for (n, _), ser in sorted(self._series.items())
                if n == name and _labels_match(ser.labels, labels)
            ]
            if not matches:
                out["kind"] = None
                out["resolution"] = None
                return out
            lv0 = self._pick_level(matches[0], window_s, resolution)
            out["kind"] = matches[0].kind
            out["resolution"] = lv0.res
            for ser in matches:
                lv = self._pick_level(ser, window_s, resolution)
                cells = self._cells_in_window_locked(ser, lv, window_s, now)
                pts = []
                for t_cell, idx in cells:
                    # the open cell's width is the elapsed fraction
                    width = min(lv.res, max(now - t_cell, 1e-9))
                    if ser.kind == "counter":
                        pts.append({
                            "t": t_cell,
                            "delta": lv.a[idx],
                            "rate": lv.a[idx] / width,
                        })
                    elif ser.kind == "gauge":
                        pts.append({
                            "t": t_cell,
                            "last": lv.a[idx],
                            "min": lv.b[idx],
                            "max": lv.c[idx],
                        })
                    else:
                        p = {
                            "t": t_cell,
                            "count": lv.a[idx],
                            "sum": lv.b[idx],
                            "rate": lv.a[idx] / width,
                        }
                        if q is not None and ser.bounds and lv.h[idx]:
                            p["q"] = percentile_from_buckets(
                                ser.bounds, lv.h[idx], lv.a[idx], q
                            )
                        pts.append(p)
                out["series"].append({"labels": ser.labels, "points": pts})
        return out

    def window_rate(
        self,
        name: str,
        labels: Optional[Dict[str, str]],
        window_s: float,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Per-second rate over the window: counter deltas (or
        histogram count deltas) summed across matching series, divided
        by the window width. None when no data landed in the window."""
        now = self._clock() if now is None else float(now)
        total = 0.0
        seen = False
        with self._lock:
            for (n, _), ser in self._series.items():
                if n != name or not _labels_match(ser.labels, labels):
                    continue
                if ser.kind == "gauge":
                    continue
                lv = self._pick_level(ser, window_s, None)
                cells = self._cells_in_window_locked(ser, lv, window_s, now)
                if cells:
                    seen = True
                total += sum(lv.a[idx] for _, idx in cells)
        if not seen:
            return None
        return total / max(window_s, 1e-9)

    def window_quantile(
        self,
        name: str,
        labels: Optional[Dict[str, str]],
        window_s: float,
        q: float,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Windowed percentile: histogram cell bucket-deltas merged
        across the window and across matching series."""
        now = self._clock() if now is None else float(now)
        merged: Optional[List[float]] = None
        count = 0.0
        bounds: Optional[List[float]] = None
        with self._lock:
            for (n, _), ser in self._series.items():
                if (
                    n != name
                    or ser.kind != "histogram"
                    or ser.bounds is None
                    or not _labels_match(ser.labels, labels)
                ):
                    continue
                if bounds is None:
                    bounds = ser.bounds
                    merged = [0.0] * len(bounds)
                elif ser.bounds != bounds:
                    continue  # conflicting layouts never mis-merge
                lv = self._pick_level(ser, window_s, None)
                for _, idx in self._cells_in_window_locked(
                    ser, lv, window_s, now
                ):
                    cell = lv.h[idx]
                    if cell is None:
                        continue
                    for i, c in enumerate(cell):
                        merged[i] += c
                    count += lv.a[idx]
        if bounds is None or count <= 0:
            return None
        return percentile_from_buckets(bounds, merged, count, q)

    def value_points(
        self,
        name: str,
        labels: Optional[Dict[str, str]],
        window_s: float,
        now: Optional[float] = None,
        max_points: Optional[int] = None,
    ) -> List[Tuple[float, float]]:
        """The (t, value) series a trend test runs over: gauge cells
        yield their last value; counter/histogram cells their rate —
        summed across matching series per cell start. ``max_points``
        coarsens the resolution so the window yields at most that many
        cells (the trend test's Theil-Sen is O(n^2) pairs and runs
        every evaluator tick — 600 base cells would be 180k slopes)."""
        now = self._clock() if now is None else float(now)
        res_hint = (
            window_s / max_points if max_points and max_points > 0 else None
        )
        acc: Dict[float, float] = {}
        with self._lock:
            for (n, _), ser in self._series.items():
                if n != name or not _labels_match(ser.labels, labels):
                    continue
                lv = self._pick_level(ser, window_s, res_hint)
                for t_cell, idx in self._cells_in_window_locked(
                    ser, lv, window_s, now
                ):
                    if ser.kind == "gauge":
                        v = lv.a[idx]
                    else:
                        width = min(lv.res, max(now - t_cell, 1e-9))
                        v = lv.a[idx] / width
                    acc[t_cell] = acc.get(t_cell, 0.0) + v
        return sorted(acc.items())

    def trend(
        self,
        name: str,
        labels: Optional[Dict[str, str]],
        window_s: float,
        now: Optional[float] = None,
        min_points: int = 4,
        max_points: int = 64,
    ) -> Optional[dict]:
        """Robust monotonic-slope verdict over the window: Theil-Sen
        median slope + the up/down concordance fractions, over at most
        ``max_points`` cells (coarser levels for longer windows — the
        O(n^2) slope estimator runs every evaluator tick). None when
        the window holds fewer than ``min_points`` cells — a two-point
        'trend' is a coin flip, not a leak."""
        pts = self.value_points(
            name, labels, window_s, now, max_points=max_points
        )
        if len(pts) < max(2, int(min_points)):
            return None
        slope = theil_sen(pts)
        if slope is None:
            return None
        frac_up, frac_down = monotonic_fractions([v for _, v in pts])
        return {
            "slope_per_s": slope,
            "n": len(pts),
            "frac_up": frac_up,
            "frac_down": frac_down,
            "first": pts[0][1],
            "last": pts[-1][1],
        }

    # -- shipping / disclosure --

    def export_ring(
        self,
        window_s: float = 600.0,
        resolution: Optional[float] = None,
        now: Optional[float] = None,
        max_series: int = 256,
    ) -> dict:
        """JSON-able down-sampled dump of every tracked metric over the
        window — the unit a node ships over the report plane and a
        bundle embeds. Bounded twice: the window picks one level, and
        ``max_series`` caps the payload (drop count disclosed)."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            names = sorted({n for n, _ in self._series})
        metrics: Dict[str, dict] = {}
        shipped = 0
        truncated = 0
        for name in names:
            r = self.query(
                name, None, window_s=window_s, resolution=resolution, now=now
            )
            if not r["series"]:
                continue
            if shipped + len(r["series"]) > max_series:
                truncated += len(r["series"])
                continue
            shipped += len(r["series"])
            metrics[name] = {
                "kind": r["kind"],
                "resolution": r["resolution"],
                "series": r["series"],
            }
        return {
            "t": now,
            "window_s": window_s,
            "resolutions": [list(rs) for rs in self.resolutions],
            "series": shipped,
            "series_truncated": truncated,
            "metrics": metrics,
        }

    def snapshot(self) -> dict:
        """Retention-config + occupancy disclosure (/debug/snapshot)."""
        with self._lock:
            return {
                "resolutions": [
                    {"res_s": r, "slots": s, "span_s": r * s}
                    for r, s in self.resolutions
                ],
                "series": len(self._series),
                "series_dropped": len(self._dropped),
                "max_series_per_metric": self.max_series_per_metric,
                "max_series_total": self.max_series_total,
                "folds": self._folds,
                "last_fold_t": (
                    None if self._last_fold == -float("inf")
                    else self._last_fold
                ),
            }


# -- the process default store (bound to the default registry) --

_default_lock = threading.Lock()
_default_store: Optional[HistoryStore] = None  # guarded-by: _default_lock


def default_store() -> HistoryStore:
    """The process default store over the default registry. Rebinds
    after ``Postoffice.reset()`` (a store over an orphaned registry is
    replaced), so tests stay hermetic like the registry itself."""
    reg = telemetry_registry.default_registry()
    global _default_store
    with _default_lock:
        if _default_store is None or _default_store.registry is not reg:
            _default_store = HistoryStore(reg).install()
        return _default_store


def installed_store() -> Optional[HistoryStore]:
    """The default store if one is live for the CURRENT registry —
    never creates (bundle capture must not conjure an empty history)."""
    reg = telemetry_registry.default_registry()
    with _default_lock:
        if _default_store is not None and _default_store.registry is reg:
            return _default_store
        return None


def set_default_store(store: Optional[HistoryStore]) -> Optional[HistoryStore]:
    """Swap the process default store (fake-clock drills/tests install
    a store whose clock they control); returns the previous one. Pass
    None to restore lazy binding."""
    global _default_store
    with _default_lock:
        prev, _default_store = _default_store, store
        return prev


def reset_default_store() -> None:
    with _default_lock:
        global _default_store
        _default_store = None
