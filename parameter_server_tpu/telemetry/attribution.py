"""Critical-path attribution over a merged span timeline.

Answers the ROADMAP's standing question — *which resource binds this
run?* — from the trace itself instead of hand-computed breakdowns (the
BENCH_r05 "75% upload-bound at 107.4 B/ex" arithmetic). Every span in
a timeline maps to one of eight categories:

    host_prep       parse/localize/remap/stack on host CPU
    encode          compact-wire encode (learner/wire.py, prep pool)
    upload          host→device staging (the tunnel/link wire time)
    network         host-wire frames between nodes (Van.transfer — the
                    control-plane/metric-report wire legs; distinct
                    from ``upload``, the host→device link)
    queue_wait      time a unit sat waiting — executor queue, serve
                    admission queue, pipeline hand-off gaps
    device_compute  executor run + materialize (XLA step + forcing)
    decode          served LM generation (the speculative lane)
    reply           completion hand-back to the waiting client

Two complementary views are computed:

- **resource view** (:func:`summarize`): busy seconds per category over
  a wall-clock window → per-resource *utilization* (busy/wall) and
  *shares* (busy/Σ stage busy). The binding resource is the stage
  category with the most busy time; at high pipeline efficiency its
  utilization approaches 1.0 — the pipeline is that resource.
- **flow view** (:func:`attribute_flows`): per flow id (one batch /
  launch / request), the spans ordered in time form the unit's
  critical path; gaps between consecutive spans are queue-wait. The
  median per-category share across flows says where a *typical* step
  or request spends its life — queueing is visible here even when
  every resource looks idle.

``executor.step`` events (system/executor.py) are expanded into their
three phases (queue-wait / run / materialize) before analysis, so the
logical-clock spans PR 1 already emits join the same timeline without
the executor knowing about categories.

`bench.py` embeds :func:`summarize`'s output as the ``attribution``
section of every record (doc/PERFORMANCE.md names it the required
evidence format for perf claims); ``script/bench_diff.py`` guards the
resulting trajectory against silent regression.
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .timeline import _start_end, events_window, flows

CATEGORIES = (
    "host_prep",
    "encode",
    "upload",
    "network",
    "queue_wait",
    "device_compute",
    "decode",
    "reply",
)

#: categories that are physical resources a pipeline can saturate (the
#: binding resource is named among these; queue_wait/reply are symptoms)
RESOURCE_CATEGORIES = (
    "host_prep", "encode", "upload", "network", "device_compute", "decode",
)

#: device-track events (utils/profiling.device_track_events — a merged
#: jax.profiler capture) are named ``device.<op>`` on ``device:<pid>``
#: threads. They are deliberately OUTSIDE the category map: their wall
#: time is already billed to device_compute through the executor
#: run/materialize phases, so categorizing them would double-count.
#: Instead :func:`device_breakdown` turns them into the per-kernel
#: sub-breakdown of device_compute that :func:`summarize` attaches as
#: ``device_compute_breakdown`` whenever a device track is present.
DEVICE_TRACK_PREFIX = "device."

#: span-name prefix → category. Longest prefix wins; names outside the
#: map contribute to the timeline but not to attribution.
NAME_CATEGORIES: Dict[str, str] = {
    "bench.prep": "host_prep",
    "bench.stack": "host_prep",
    "bench.device": "device_compute",
    "bench.upload": "upload",
    "ingest.read": "host_prep",
    "ingest.filter": "host_prep",
    "ingest.prep": "host_prep",
    "ingest.upload": "upload",
    "wire.encode": "encode",
    "van.transfer": "network",
    "executor.queue_wait": "queue_wait",
    "executor.run": "device_compute",
    "executor.materialize": "device_compute",
    # serve.coalesce.flush is deliberately ABSENT: the flush span wraps
    # the union merge + store pull whose real work is already attributed
    # through the flush flow's own executor.step expansion — mapping the
    # wrapper would bill the same interval twice
    "serve.decode": "decode",
    "serve.execute": "host_prep",  # predict lane: host gather math
    "serve.reply": "reply",
}


def categorize(name: str) -> Optional[str]:
    if name.startswith(DEVICE_TRACK_PREFIX):
        return None  # device track: handled by device_breakdown
    best: Optional[str] = None
    best_len = -1
    for prefix, cat in NAME_CATEGORIES.items():
        if name.startswith(prefix) and len(prefix) > best_len:
            best, best_len = cat, len(prefix)
    return best


def is_device_event(ev: Dict[str, Any]) -> bool:
    """True for merged device-track spans (``device.<op>`` names on a
    ``device:<pid>`` thread)."""
    return str(ev.get("name", "")).startswith(DEVICE_TRACK_PREFIX) or str(
        ev.get("thread", "")
    ).startswith("device:")


def categorize_event(ev: Dict[str, Any]) -> Optional[str]:
    """Category of one span event. Name-prefix lookup, with one
    event-aware override: a ``serve.execute`` span whose ``req`` is a
    pull spends its life blocked on the shared read machinery (replica
    miss → coalescer window deadline → store round trip inside
    PullTicket.result), so it is queue-wait from the request's point of
    view — the store-side work itself is attributed by the flush flow's
    executor.step expansion. Predict execution (host gather + margin
    math on the worker thread) stays host_prep."""
    name = str(ev.get("name", ""))
    if name == "serve.execute" and ev.get("req") == "pull":
        return "queue_wait"
    return categorize(name)


def expand_executor_steps(
    events: Iterable[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Replace each ``executor.step`` event with three phase spans
    (queue-wait → run → materialize) laid back from its finish time —
    the event's ``t_wall`` is stamped when the step finishes and
    ``total_s`` spans submit→finish, so the phases tile the interval
    in order. Other events pass through unchanged."""
    out: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("name") != "executor.step":
            out.append(ev)
            continue
        t_end = float(ev.get("t_wall", 0.0))
        total = float(ev.get("total_s", 0.0))
        qw = float(ev.get("queue_wait_s", 0.0))
        run_s = float(ev.get("run_s", 0.0))
        mat_s = float(ev.get("materialize_s", 0.0))
        t0 = t_end - total
        carry = {
            k: ev[k] for k in ("ts", "flow", "executor", "thread") if k in ev
        }
        phases = (
            ("executor.queue_wait", t0, qw),
            ("executor.run", t0 + qw, run_s),
            ("executor.materialize", t0 + qw + run_s, mat_s),
        )
        for name, start, dur in phases:
            if dur <= 0.0:
                continue
            out.append(
                {
                    "kind": "span",
                    "name": name,
                    "t_wall": start,
                    "dur_s": dur,
                    **carry,
                }
            )
    return out


def _clip(start: float, dur: float, window: Optional[Tuple[float, float]]) -> float:
    if window is None:
        return max(0.0, dur)
    lo, hi = window
    return max(0.0, min(start + dur, hi) - max(start, lo))


def _start_end_dur(ev: Dict[str, Any]) -> Tuple[float, float]:
    """(start, duration) of one span event — the _clip calling shape."""
    s, e = _start_end(ev)
    return s, e - s


def _merge_intervals(
    intervals: List[Tuple[float, float]],
) -> List[Tuple[float, float]]:
    intervals.sort()
    merged: List[Tuple[float, float]] = []
    for lo, hi in intervals:
        if merged and lo <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], hi))
        else:
            merged.append((lo, hi))
    return merged


def busy_by_category(
    events: Sequence[Dict[str, Any]],
    window: Optional[Tuple[float, float]] = None,
) -> Dict[str, float]:
    """Busy seconds per category (span durations, clipped to ``window``).
    Busy time is summed per category even when spans overlap across
    threads — each category models one resource (the host cores, the
    wire, the chip), and parallel spans of one category mean that
    resource is multiply subscribed, which utilization should show.

    The one exception is nesting ACROSS categories on one thread:
    ``wire.encode`` runs inside the prep call (worker.prep →
    encode_exact), so its interval sits inside a ``bench.prep`` /
    ``ingest.prep`` span on the same thread — and a ``van.transfer``
    runs inside the RPC step body the executor dispatched, so its
    interval sits inside that step's ``executor.run`` phase. Those
    seconds belong to the nested (encode / network) resource alone —
    they are carved out of the ENCLOSING span's category so one CPU
    second is never billed to two stages."""
    expanded = [
        ev for ev in expand_executor_steps(events) if not ev.get("abandoned")
    ]
    # intervals of the carve categories, per thread: encode nests in
    # host_prep wrappers, network (van.transfer) nests in the
    # executor.run phase of the RPC step that sent it
    carve_cats = ("encode", "network")
    carve_by_thread: Dict[Any, List[Tuple[float, float]]] = {}
    for ev in expanded:
        if categorize_event(ev) in carve_cats:
            s = float(ev.get("t_wall", 0.0))
            carve_by_thread.setdefault(ev.get("thread"), []).append(
                (s, s + float(ev.get("dur_s", 0.0)))
            )
    carve_by_thread = {
        t: _merge_intervals(iv) for t, iv in carve_by_thread.items()
    }
    busy = {cat: 0.0 for cat in CATEGORIES}
    for ev in expanded:
        cat = categorize_event(ev)
        if cat is None:
            continue
        s = float(ev.get("t_wall", 0.0))
        d = float(ev.get("dur_s", 0.0))
        sec = _clip(s, d, window)
        if cat not in carve_cats:
            for lo, hi in carve_by_thread.get(ev.get("thread"), ()):
                ov_lo, ov_hi = max(lo, s), min(hi, s + d)
                if ov_hi > ov_lo:
                    sec -= _clip(ov_lo, ov_hi - ov_lo, window)
        busy[cat] += max(0.0, sec)
    return busy


def _span_self_times(spans: List[Dict[str, Any]]):
    """Yield ``(event, self_s)`` per span of ONE track: duration minus
    time covered by child spans nested inside it (same stack pass as
    utils/profiling._self_times, over span dicts) — a ``while``/
    ``fusion`` wrapper is credited only the time its body ops leave."""
    evs = sorted(
        spans,
        key=lambda e: (
            float(e.get("t_wall", 0.0)), -float(e.get("dur_s", 0.0) or 0.0)
        ),
    )
    stack: List[list] = []  # [event, end_t, child_s]
    for ev in evs:
        t0 = float(ev.get("t_wall", 0.0))
        dur = float(ev.get("dur_s", 0.0) or 0.0)
        while stack and t0 >= stack[-1][1]:
            top, _, child = stack.pop()
            yield top, float(top.get("dur_s", 0.0) or 0.0) - child
        if stack:
            stack[-1][2] += dur
        stack.append([ev, t0 + dur, 0.0])
    while stack:
        top, _, child = stack.pop()
        yield top, float(top.get("dur_s", 0.0) or 0.0) - child


def device_breakdown(
    events: Sequence[Dict[str, Any]],
    window: Optional[Tuple[float, float]] = None,
    top: int = 8,
) -> Optional[Dict[str, Any]]:
    """Per-kernel sub-breakdown of device_compute from a merged device
    track, or None when the trace carries no device events.

    Busy time is per-kernel SELF time (nesting carved out, per device
    thread); ``gap_s`` is the device wall window minus the union of op
    intervals — a kernel-dominated capture shows ``busy_frac`` near
    1.0, a dispatch-bound one shows the gaps the ROADMAP's "where do
    the other 96% go" question is about. ``shares`` normalize over
    total device busy time (the device_compute analog of the resource
    view's ``shares``)."""
    dev = [
        ev for ev in events
        if is_device_event(ev) and not ev.get("abandoned")
    ]
    if not dev:
        return None
    if window is None:
        window = events_window(dev)
    wall = max(0.0, window[1] - window[0])
    by_thread: Dict[Any, List[Dict[str, Any]]] = {}
    for ev in dev:
        by_thread.setdefault(ev.get("thread"), []).append(ev)
    per_kernel: Dict[str, List[float]] = {}
    intervals: List[Tuple[float, float]] = []
    busy_total = 0.0
    for track in by_thread.values():
        for ev in track:
            s = float(ev.get("t_wall", 0.0))
            d = float(ev.get("dur_s", 0.0) or 0.0)
            lo, hi = max(s, window[0]), min(s + d, window[1])
            if hi > lo:
                intervals.append((lo, hi))
        for ev, self_s in _span_self_times(track):
            sec = min(self_s, _clip(
                float(ev.get("t_wall", 0.0)), float(ev.get("dur_s", 0.0) or 0.0),
                window,
            ))
            if sec <= 0.0:
                continue
            name = str(ev.get("name", "?"))
            if name.startswith(DEVICE_TRACK_PREFIX):
                name = name[len(DEVICE_TRACK_PREFIX):]
            rec = per_kernel.setdefault(name, [0.0, 0])
            rec[0] += sec
            rec[1] += 1
            busy_total += sec
    covered = sum(hi - lo for lo, hi in _merge_intervals(intervals))
    out: Dict[str, Any] = {
        "device_busy_s": round(busy_total, 6),
        "wall_s": round(wall, 6),
        "gap_s": round(max(0.0, wall - covered), 6),
        "busy_frac": round(covered / wall, 4) if wall > 0 else None,
        "tracks": len(by_thread),
    }
    if busy_total > 0:
        ranked = sorted(per_kernel.items(), key=lambda kv: -kv[1][0])
        out["kernels"] = [
            {
                "name": k,
                "ms": round(v[0] * 1e3, 4),
                "calls": v[1],
                "share": round(v[0] / busy_total, 4),
            }
            for k, v in ranked[:top]
        ]
    return out


def flow_critical_path(seq: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """One flow's path through the pipeline: spans in time order, gaps
    between consecutive spans charged to queue-wait (a gap immediately
    before a ``reply`` span is charged to reply — the hand-back leg).
    Returns ``{"total_s", "by_category": {...}}``."""
    spans = [
        ev
        for ev in expand_executor_steps(seq)
        if not ev.get("abandoned")
    ]
    spans.sort(key=lambda e: float(e.get("t_wall", 0.0)))
    by_cat = {cat: 0.0 for cat in CATEGORIES}
    cursor: Optional[float] = None
    first = last = None
    for ev in spans:
        start = float(ev.get("t_wall", 0.0))
        dur = float(ev.get("dur_s", 0.0))
        cat = categorize_event(ev)
        if first is None:
            first = start
        if cursor is not None and start > cursor:
            gap_cat = "reply" if cat == "reply" else "queue_wait"
            by_cat[gap_cat] += start - cursor
        if cat is not None:
            # only the portion past the cursor extends the critical
            # path — overlapped work (pipelining) is not path time
            base = start if cursor is None else max(start, cursor)
            by_cat[cat] += max(0.0, start + dur - base)
        cursor = start + dur if cursor is None else max(cursor, start + dur)
        last = cursor
    total = (last - first) if (first is not None and last is not None) else 0.0
    return {"total_s": total, "by_category": by_cat}


def attribute_flows(
    events: Sequence[Dict[str, Any]],
    window: Optional[Tuple[float, float]] = None,
) -> Dict[str, Any]:
    """Median per-category critical-path share across every flow in the
    trace, plus the dominant category — where a typical unit of work
    spends its life (queue-wait included, unlike the resource view).
    With ``window``, only flows with at least one span intersecting it
    are counted (each qualifying flow's path is measured whole — a flow
    straddling the boundary is not truncated); warmup or serialized
    breakdown-phase flows outside the measured window stay out of the
    median."""
    by_flow = flows(events)
    shares: Dict[str, List[float]] = {cat: [] for cat in CATEGORIES}
    totals: List[float] = []
    for seq in by_flow.values():
        if window is not None and not any(
            _clip(s, e - s, window) > 0.0 or window[0] <= s <= window[1]
            for s, e in (_start_end(ev) for ev in seq)
        ):
            continue
        cp = flow_critical_path(seq)
        if cp["total_s"] <= 0.0:
            continue
        if sum(cp["by_category"].values()) <= 0.0:
            # a flow with NO attributable path time says nothing about
            # where a unit spends its life — e.g. a coalescer flush
            # flow, whose only duration-bearing span is the deliberately
            # uncategorized serve.coalesce.flush wrapper (the executor
            # phases nest inside it and extend the path by ~nothing);
            # letting it in would dilute every category's share list
            # with zeros and inflate count with non-request units
            continue
        totals.append(cp["total_s"])
        for cat in CATEGORIES:
            shares[cat].append(cp["by_category"][cat] / cp["total_s"])
    if not totals:
        return {"count": 0}
    med = {
        cat: round(statistics.median(vals), 4)
        for cat, vals in shares.items()
        if vals and statistics.median(vals) > 0.0
    }
    dominant = max(med, key=med.get) if med else None
    return {
        "count": len(totals),
        "median_total_s": round(statistics.median(totals), 6),
        "critical_path_shares": med,
        "dominant": dominant,
    }


def summarize(
    events: Sequence[Dict[str, Any]],
    window: Optional[Tuple[float, float]] = None,
) -> Dict[str, Any]:
    """The record-embeddable attribution section.

    ``shares`` normalizes stage busy time over the resource categories
    (comparable to the old hand-derived ``breakdown_fracs``);
    ``utilization`` divides by the wall window (1.0 = that resource ran
    the whole time — it IS the pipeline); ``binding_resource`` names
    the stage category with the most busy time and quotes its
    utilization. The per-flow critical-path view rides along under
    ``flows``.
    """
    # expand once up front: re-expansion downstream (busy_by_category,
    # flow_critical_path) passes already-expanded phase spans through
    # unchanged, so the O(events) rebuild happens a single time
    events = expand_executor_steps(events)
    if window is None:
        window = events_window(events)
    wall = max(0.0, window[1] - window[0])
    busy = busy_by_category(events, window)
    stage_busy = {cat: busy[cat] for cat in RESOURCE_CATEGORIES}
    stage_total = sum(stage_busy.values())
    abandoned = sum(1 for ev in events if ev.get("abandoned"))
    out: Dict[str, Any] = {
        "wall_s": round(wall, 6),
        "busy_s": {
            cat: round(sec, 6) for cat, sec in busy.items() if sec > 0.0
        },
        "queue_wait_s": round(busy["queue_wait"], 6),
        "abandoned_spans": abandoned,
        "flows": attribute_flows(events, window),
    }
    # the per-kernel view of where device_compute itself goes — present
    # only when a profiler capture's device track was merged into this
    # timeline; records without one are unchanged. Gap accounting runs
    # over the device TRACK's own extent (a capture covers one launch,
    # not the whole bench window — clipping to `window` would charge
    # every non-captured second as device gap).
    dev_events = [
        ev for ev in events
        if is_device_event(ev)
        and (window is None or _clip(*_start_end_dur(ev), window) > 0.0)
    ]
    dev = device_breakdown(dev_events) if dev_events else None
    if dev is not None:
        out["device_compute_breakdown"] = dev
    if stage_total > 0.0:
        out["shares"] = {
            cat: round(sec / stage_total, 4)
            for cat, sec in stage_busy.items()
            if sec > 0.0
        }
        binding = max(stage_busy, key=stage_busy.get)
        out["binding_resource"] = binding
        if wall > 0.0:
            out["utilization"] = {
                cat: round(sec / wall, 4)
                for cat, sec in stage_busy.items()
                if sec > 0.0
            }
            out["binding_utilization"] = round(stage_busy[binding] / wall, 4)
    return out


def summarize_trace(
    jsonl_path: str, window: Optional[Tuple[float, float]] = None
) -> Dict[str, Any]:
    """:func:`summarize` over a JSONL trace file."""
    from .timeline import load_events

    return summarize(load_events(jsonl_path), window)
