"""Learning truth plane: realized staleness, key heat & shard balance,
and cluster-wide convergence telemetry.

Five observability planes watch the *system* — seconds (PR 1/7), bytes
(PR 10/12), FLOPs (PR 11), incidents (PR 13) — but none watch the
*learning*. The bounded-delay contract (``SGDConfig.max_delay`` = τ) is
configured yet never measured; which key ranges run hot is exactly the
input a declarative partitioner needs; and a NaN'd table serves 200s
all day. This module makes those first-class, the way PR 11 did for the
chip:

- **Realized staleness** (:meth:`LearningPlane.note_submit`): each
  submitted step is stamped with how many ministeps its weight snapshot
  lags the apply clock — ``ps_learning_staleness_ministeps`` is the
  per-worker histogram — and, separately, the executor logical-clock
  lag between the snapshot-taking submission and this one (the
  ``Executor`` timestamps the worker already holds; disclosed as
  ``executor_clock_lag_max``, not folded into the histogram: τ is a
  ministep bound and the launch-clock lag never exceeds it). The
  observed-max gauge against τ turns the bounded-delay contract into a
  measured invariant — it meters the same counter the snapshot refresh
  enforces, so it is a regression detector for the ENFORCEMENT (a
  skipped or mis-scheduled refresh reads > τ and fires), not an
  independent oracle of it (bench records assert ``observed <= τ``
  in-record; the ``staleness_breach`` rule fires live on
  ``ps_learning_staleness_over_tau > 0``). Since PR 20 the bound is
  the LIVE τ: each submission is judged against the effective τ in
  force when it was stamped (:meth:`LearningPlane.set_tau`; the
  adaptive controller moves it between submissions), so a submission
  that was legal under the wide τ of its era never false-fires after
  the controller clamps down — and the current τ itself is exported
  as the ``ps_consistency_tau`` gauge.
- **Key heat & shard balance** (:class:`KeyHeat` /
  :meth:`LearningPlane.note_slots`): a windowed-decay count-min sketch
  (``utils/sketch.DecayCountMin`` — the same CM machinery the ingest
  tail filter rides) over pushed/pulled table slots, fed from the
  single-owner feeder/uploader threads (the stateless-or-feeder rule's
  lock-annotated arm: appends are one lock + vectorized numpy). Slot
  counts fold by server key range (``system/assigner.NodeAssigner``
  Ranges) into per-shard load shares, an imbalance ratio gauge
  (max/mean), and a top-k hot-slot table served in ``/debug/snapshot``.
- **Convergence** (:meth:`LearningPlane.note_step`): per-step loss /
  grad-norm / update-norm / weight-norm arrive as cheap in-jit side
  outputs of the existing step builders (trace-pure scalars on the
  metrics dict — the PR 8 jit-purity pattern; donation-safe) and are
  metered HERE, host-side, in ``ISGDCompNode.collect``. Divergence is
  judged per collect — non-finite loss/gradient, or a grad norm far
  past its recent median (a seeded LR blow-up) — and ticks
  ``ps_learning_divergence_total``, which the shipped
  ``loss_divergence`` rule fires on (a firing transition captures a
  flight-recorder bundle through the PR 13 trigger plane).

Cluster view: a plane's :meth:`LearningPlane.export` is a plain-dict
registry export of the ``ps_learning_*`` family, wire-safe for the
restricted unpickler; :class:`ClusterFeedMaster` receives those
reports over the typed ``MonitorMaster``/``MonitorSlaver.over_van``
path and feeds the PR 10 :class:`~.aggregate.ClusterAggregator`, so one
``/metrics`` scrape shows ``ps_learning_*`` node-labeled with the
cluster rollup. ``doc/OBSERVABILITY.md`` ("Learning truth plane")
documents how to read all of it.
"""

from __future__ import annotations

import collections
import math
import threading
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

from . import registry as telemetry_registry

#: trajectory points kept per plane (loss/grad-norm tail for the bench
#: record's ``learning`` section; the full stream rides the metrics)
TRAJECTORY_CAP = 512

#: grad-norm spike factor: a collected step whose grad norm exceeds
#: this multiple of the recent median counts as divergence
#: (reason="spike"); generous so warmup transients never false-fire
SPIKE_FACTOR = 100.0

#: collected steps needed before the spike judge activates (a median
#: over fewer points is warmup noise, not a baseline)
SPIKE_MIN_WINDOW = 8


def _shard_starts(num_slots: int, num_shards: int) -> np.ndarray:
    """Per-shard slot-range begin offsets, derived through the SAME
    assignment the servers use (system/assigner.NodeAssigner handing
    out Range.even_divide key ranges) — the heat fold must agree with
    the table's actual ownership, not re-derive its own arithmetic."""
    from ..system.assigner import NodeAssigner
    from ..system.manager import Node
    from ..utils.range import Range

    assigner = NodeAssigner(num_shards, Range(0, num_slots))
    starts = []
    for i in range(num_shards):
        node = assigner.assign(Node(Node.SERVER, i))
        starts.append(int(node.key_range.begin))
    return np.asarray(starts, dtype=np.int64)


# owner-thread: feeder
class KeyHeat:
    """Windowed key-heat accounting over table slots.

    One :class:`~..utils.sketch.DecayCountMin` estimates per-slot
    recent frequency (top-k hot-slot table); an exact per-shard count
    vector — folded by the servers' assigned key ranges — carries the
    load shares and the imbalance ratio. ``decay_every`` notes advance
    the window (counters halve), so a key that cooled falls out of the
    view instead of being pinned by its history.

    Thread-safety: ``note`` is called from the worker's feeder/trainer
    thread, reads from scrape/snapshot threads — every member is
    guarded by one small lock (the stateless-or-feeder rule's
    lock-annotated arm; the insert itself is vectorized numpy).
    """

    def __init__(
        self,
        num_slots: int,
        num_shards: int,
        sketch_slots: int = 1 << 16,
        hashes: int = 2,
        top_k: int = 16,
        decay_every: int = 256,
    ):
        from ..utils.sketch import DecayCountMin

        self.num_slots = int(num_slots)
        self.num_shards = int(num_shards)
        self.top_k = int(top_k)
        self.decay_every = int(decay_every)
        self._starts = _shard_starts(num_slots, num_shards)
        self._sketch = DecayCountMin(n=sketch_slots, k=hashes)  # guarded-by: _lock
        self._shard_counts = np.zeros(num_shards, np.float64)  # guarded-by: _lock
        self._candidates: Dict[int, float] = {}  # guarded-by: _lock
        self._notes = 0  # guarded-by: _lock
        self._slots_total = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def note(self, slots: np.ndarray) -> int:
        """Fold one batch's slot ids in; returns how many were counted
        (sentinel/padding slots >= num_slots are dropped)."""
        slots = np.asarray(slots).reshape(-1)
        if slots.size == 0:
            return 0
        slots = slots[(slots >= 0) & (slots < self.num_slots)]
        if slots.size == 0:
            return 0
        uniq, cnt = np.unique(slots, return_counts=True)
        with self._lock:
            self._sketch.insert(uniq, cnt)
            shard_idx = (
                np.searchsorted(self._starts, uniq, side="right") - 1
            )
            np.add.at(self._shard_counts, shard_idx, cnt.astype(np.float64))
            # candidate tracking: this batch's unique slots carry their
            # CURRENT sketch estimates; the dict keeps a generous
            # superset of the top-k and snapshot() re-queries it so the
            # served table reflects decay, not stale insert-time counts
            est = self._sketch.query(uniq)
            order = np.argsort(est)[::-1][: 4 * self.top_k]
            for s, e in zip(uniq[order], est[order]):
                self._candidates[int(s)] = float(e)
            if len(self._candidates) > 8 * self.top_k:
                keep = sorted(
                    self._candidates.items(), key=lambda kv: -kv[1]
                )[: 4 * self.top_k]
                self._candidates = dict(keep)
            self._notes += 1
            self._slots_total += int(slots.size)
            if self.decay_every and self._notes % self.decay_every == 0:
                self._decay_locked()
        return int(slots.size)

    def _decay_locked(self) -> None:  # holds-lock: _lock
        self._sketch.decay()
        self._shard_counts *= 0.5
        self._candidates = {
            s: v * 0.5 for s, v in self._candidates.items() if v >= 2.0
        }

    def advance(self) -> None:
        """Explicitly advance one decay window (tests, timers)."""
        with self._lock:
            self._decay_locked()

    def rebase(self, perm: Optional[np.ndarray] = None) -> None:
        """Start a fresh measurement window after a layout change (a
        live rebalance moved rows, so the accumulated per-shard counts
        describe the OLD slot→shard assignment and must not leak into
        the post-rebalance imbalance reading). The sketch and exact
        shard counts reset; with ``perm`` (old slot → new slot) the
        hot-slot candidate set is translated so the hot keys stay
        identified across the move, otherwise it clears too."""
        with self._lock:
            self._sketch.clear()
            self._shard_counts[:] = 0.0
            if perm is None:
                self._candidates = {}
            else:
                perm = np.asarray(perm)
                self._candidates = {
                    int(perm[s]): v
                    for s, v in self._candidates.items()
                    if 0 <= s < len(perm)
                }
            self._notes = 0
            self._slots_total = 0

    def estimate(self, slots: np.ndarray) -> np.ndarray:
        """Sketch frequency estimates for the given slots (upper-biased
        CM semantics; the parity probe compares these against exact
        counts on a small run)."""
        with self._lock:
            return self._sketch.query(np.asarray(slots).reshape(-1))

    def shares(self) -> Dict[str, Any]:
        """Per-shard load shares + the max/mean imbalance ratio."""
        with self._lock:
            counts = self._shard_counts.copy()
        total = float(counts.sum())
        if total <= 0:
            return {
                "total_weight": 0.0,
                "shares": [0.0] * self.num_shards,
                "imbalance": None,
            }
        shares = counts / total
        return {
            "total_weight": round(total, 1),
            "shares": [round(float(s), 5) for s in shares],
            "imbalance": round(float(counts.max() / counts.mean()), 4),
        }

    def top_slots(self, k: Optional[int] = None) -> List[Dict[str, Any]]:
        """The hot-slot table: top-k candidate slots by current sketch
        estimate, with the owning shard."""
        k = self.top_k if k is None else k
        with self._lock:
            cand = np.fromiter(self._candidates, dtype=np.int64)
            if cand.size == 0:
                return []
            est = self._sketch.query(cand)
        order = np.argsort(est)[::-1][:k]
        out = []
        for i in order:
            slot = int(cand[i])
            shard = int(
                np.searchsorted(self._starts, slot, side="right") - 1
            )
            out.append({"slot": slot, "est": float(est[i]), "shard": shard})
        return out


class LearningPlane:
    """One worker's learning-truth accounting against a registry.

    Created by the training workers (``AsyncSGDWorker`` registers one
    under its node name against the process default registry; cluster
    tests hand each logical worker a private registry so the monitor
    path can ship node-distinct exports). All mutable state is guarded
    by one lock; the metered hot paths are a handful of scalar ops per
    submitted/collected step plus one vectorized sketch insert per
    noted batch.
    """

    def __init__(
        self,
        worker: str,
        num_slots: int,
        num_shards: int,
        max_delay: int,
        registry=None,
        heat_every: int = 1,
        spike_factor: float = SPIKE_FACTOR,
    ):
        from .instruments import consistency_instruments, learning_instruments

        self.worker = worker
        self.max_delay = int(max_delay)
        self.tau = int(max_delay)  # live effective τ; see set_tau()
        self.heat_every = max(1, int(heat_every))
        self.spike_factor = float(spike_factor)
        self.registry = (
            registry
            if registry is not None
            else telemetry_registry.default_registry()
        )
        tel = learning_instruments(self.registry)
        self._staleness_hist = tel["staleness"]  # parent: reads
        self._h_staleness = tel["staleness"].labels(worker=worker)
        self._g_staleness_max = tel["staleness_max"].labels(worker=worker)
        self._g_over_tau = tel["staleness_over_tau"].labels(worker=worker)
        self._c_examples = tel["examples"].labels(worker=worker)
        self._g_loss = tel["loss"].labels(worker=worker)
        self._g_grad = tel["grad_norm"].labels(worker=worker)
        self._g_update = tel["update_norm"].labels(worker=worker)
        self._g_weight = tel["weight_norm"].labels(worker=worker)
        self._c_divergence = tel["divergence"]
        self._c_heat = tel["heat_slots"].labels(worker=worker)
        self._g_share = tel["shard_share"]
        self._g_imbalance = tel["shard_imbalance"]
        self._g_tau = consistency_instruments(self.registry)["tau"].labels(
            worker=worker
        )
        self._g_tau.set(self.tau)
        self.heat = KeyHeat(num_slots, num_shards)
        self._staleness_max = 0  # guarded-by: _lock
        self._over_tau_max = -int(max_delay)  # guarded-by: _lock
        self._clock_lag_max = 0  # guarded-by: _lock
        self._submits = 0  # guarded-by: _lock
        self._collects = 0  # guarded-by: _lock
        self._examples = 0  # guarded-by: _lock
        self._divergences: Dict[str, int] = {}  # guarded-by: _lock
        self._trajectory: collections.deque = collections.deque(  # guarded-by: _lock
            maxlen=TRAJECTORY_CAP
        )
        self._grad_window: collections.deque = collections.deque(  # guarded-by: _lock
            maxlen=32
        )
        self._lock = threading.Lock()
        # the observed-vs-τ gauge starts satisfied (nothing observed)
        self._g_over_tau.set(-float(self.max_delay))

    # -- realized staleness (the submit/apply path) --

    def set_tau(self, tau: int) -> None:
        """Move the LIVE effective τ (the adaptive controller's knob).

        Future submissions are judged against the new bound; already
        stamped ones keep the verdict of the τ in force when they were
        submitted (tracked per-submission in :meth:`note_submit`), so a
        clamp-down never retroactively brands legal history a breach.
        Refreshes the ``ps_consistency_tau`` gauge."""
        tau = int(tau)
        with self._lock:
            self.tau = tau
        self._g_tau.set(tau)

    def note_submit(
        self,
        staleness: int,
        n_steps: int = 1,
        clock_lag: int = 0,
        tau: Optional[int] = None,
    ) -> None:
        """Stamp one submitted step (or scan superstep) with its
        realized snapshot staleness in MINISTEPS (comparable to τ) and
        the executor logical-clock lag between the snapshot-taking
        submission and this one. ``tau`` is the effective bound at
        submit time (callers that plumb the live τ pass it explicitly;
        default is the plane's current live τ) — the over-τ gauge
        tracks the worst PER-SUBMISSION margin ``staleness - τ_then``,
        which is what the ``staleness_breach`` rule must fire on once
        τ adapts."""
        staleness = int(staleness)
        self._h_staleness.observe(staleness)
        with self._lock:
            self._submits += 1
            if staleness > self._staleness_max:
                self._staleness_max = staleness
            bound = self.tau if tau is None else int(tau)
            over = staleness - bound
            if over > self._over_tau_max:
                self._over_tau_max = over
            if clock_lag > self._clock_lag_max:
                self._clock_lag_max = int(clock_lag)
            observed = self._staleness_max
            over_max = self._over_tau_max
        self._g_staleness_max.set(observed)
        self._g_over_tau.set(over_max)

    # -- convergence (collect-side metering of in-jit side outputs) --

    def note_step(self, metrics: Mapping[str, Any], n_steps: int = 1) -> None:
        """Fold one collected step's metrics in. ``metrics`` is the
        step's host-materialized dict: ``objective``/``num_ex`` always,
        plus the optional ``grad_sq``/``update_sq``/``weight_sq`` side
        outputs (summed over ministeps for scan supersteps)."""
        objective = float(metrics.get("objective", 0.0))
        num_ex = int(metrics.get("num_ex", 0))
        grad_sq = _opt_float(metrics.get("grad_sq"))
        update_sq = _opt_float(metrics.get("update_sq"))
        weight_sq = _opt_float(metrics.get("weight_sq"))
        loss = objective / max(1, num_ex)
        grad_norm = None if grad_sq is None else _safe_sqrt(grad_sq)
        update_norm = None if update_sq is None else _safe_sqrt(update_sq)
        weight_norm = None if weight_sq is None else _safe_sqrt(weight_sq)

        nonfinite = not math.isfinite(loss) or any(
            v is not None and not math.isfinite(v)
            for v in (grad_norm, update_norm, weight_norm)
        )
        spike = False
        with self._lock:
            self._collects += 1
            self._examples += num_ex
            if not nonfinite and grad_norm is not None:
                if len(self._grad_window) >= SPIKE_MIN_WINDOW:
                    med = float(np.median(self._grad_window))
                    spike = (
                        med > 0 and grad_norm > self.spike_factor * med
                    )
                self._grad_window.append(grad_norm)
            reason = (
                "nonfinite" if nonfinite else ("spike" if spike else None)
            )
            if reason is not None:
                self._divergences[reason] = (
                    self._divergences.get(reason, 0) + 1
                )
            self._trajectory.append({
                "step": self._collects,
                "loss": _json_float(loss),
                "grad_norm": _json_float(grad_norm),
                "update_norm": _json_float(update_norm),
                "weight_norm": _json_float(weight_norm),
            })
        self._c_examples.inc(num_ex)
        if math.isfinite(loss):
            self._g_loss.set(loss)
        for gauge, v in (
            (self._g_grad, grad_norm),
            (self._g_update, update_norm),
            (self._g_weight, weight_norm),
        ):
            if v is not None and math.isfinite(v):
                gauge.set(v)
        if reason is not None:
            self._c_divergence.labels(worker=self.worker, reason=reason).inc()

    # -- key heat (feeder/uploader-thread slot stream) --

    def note_slots(self, slots: np.ndarray) -> None:
        """Fold one batch's table-slot ids into the heat sketch and the
        per-shard load accounting; refreshes the share/imbalance
        gauges. Single-owner feeder/uploader threads only (KeyHeat's
        lock covers scrape-side reads)."""
        n = self.heat.note(slots)
        if n <= 0:
            return
        self._c_heat.inc(n)
        shares = self.heat.shares()
        for i, s in enumerate(shares["shares"]):
            self._g_share.labels(shard=str(i)).set(s)
        if shares["imbalance"] is not None:
            self._g_imbalance.set(shares["imbalance"])

    # -- reads --

    def staleness_summary(self) -> Dict[str, Any]:
        with self._lock:
            observed = self._staleness_max
            over_max = self._over_tau_max
            live_tau = self.tau
            lag = self._clock_lag_max
            submits = self._submits
        count = self._staleness_hist.count(worker=self.worker)
        # percentile() of an empty histogram is NaN, and a literal NaN
        # in /debug/snapshot is invalid JSON to RFC-compliant clients —
        # a freshly-built worker must serve nulls, not break the scrape
        hist: Dict[str, Any] = {"count": count}
        for key, q in (("p50", 0.5), ("p99", 0.99)):
            hist[key] = (
                round(
                    self._staleness_hist.percentile(q, worker=self.worker),
                    3,
                )
                if count
                else None
            )
        return {
            "configured_tau": self.max_delay,
            "live_tau": live_tau,
            "observed_max": observed,
            # worst per-submission margin vs the τ in force AT SUBMIT
            # (== observed_max - configured_tau while τ never adapts)
            "over_tau_max": over_max,
            "within_bound": over_max <= 0,
            "executor_clock_lag_max": lag,
            "submits": submits,
            "histogram": hist,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The record-embeddable learning view for this worker:
        staleness summary (with the in-record bound verdict), shard
        shares + imbalance + hot slots, the convergence-trajectory
        tail, and divergence accounting."""
        with self._lock:
            traj = list(self._trajectory)
            divergences = dict(self._divergences)
            collects = self._collects
            examples = self._examples
        return {
            "worker": self.worker,
            "staleness": self.staleness_summary(),
            "shards": self.heat.shares(),
            "hot_slots": self.heat.top_slots(),
            "collected_steps": collects,
            "examples": examples,
            "divergence": divergences,
            "trajectory_tail": traj[-32:],
        }

    def export(self) -> Dict[str, dict]:
        """This plane's ``ps_learning_*`` families as a plain-dict
        registry export — the wire payload the monitor path ships to
        the cluster aggregator (restricted-unpickler-safe)."""
        export = self.registry.export_state()
        return {
            name: decl
            for name, decl in export.items()
            if name.startswith("ps_learning_")
        }


def _opt_float(v) -> Optional[float]:
    return None if v is None else float(v)


def _safe_sqrt(v: float) -> float:
    return math.sqrt(v) if math.isfinite(v) and v >= 0 else float(v)


def _json_float(v: Optional[float]) -> Optional[float]:
    """JSON-able scalar: non-finite floats become strings (a bench
    record with a literal NaN would be unparseable JSON)."""
    if v is None:
        return None
    if not math.isfinite(v):
        return str(v)
    return round(v, 6)


# -- the process plane registry --------------------------------------------

_planes_lock = threading.Lock()
_planes: Dict[str, LearningPlane] = {}  # guarded by _planes_lock


def register(plane: LearningPlane) -> LearningPlane:
    """Track a plane under its worker name (latest wins — workers are
    rebuilt per run/test and a fresh plane binds the current registry)."""
    with _planes_lock:
        _planes[plane.worker] = plane
    return plane


def plane(
    worker: str,
    num_slots: int,
    num_shards: int,
    max_delay: int,
    registry=None,
    **kw,
) -> LearningPlane:
    """Create + register a fresh plane for a worker (the AsyncSGDWorker
    entry point)."""
    return register(LearningPlane(
        worker, num_slots, num_shards, max_delay, registry=registry, **kw
    ))


def get_plane(worker: str) -> Optional[LearningPlane]:
    with _planes_lock:
        return _planes.get(worker)


def planes() -> Dict[str, LearningPlane]:
    with _planes_lock:
        return dict(_planes)


def reset() -> None:
    """Test hermeticity: drop every registered plane."""
    with _planes_lock:
        _planes.clear()


def snapshot_all() -> Dict[str, Any]:
    """Every live plane's snapshot, keyed by worker — the ``learning``
    member of ``/debug/snapshot`` (hot-slot tables included)."""
    return {name: p.snapshot() for name, p in sorted(planes().items())}


# -- cluster wiring (the typed monitor path into the PR 10 aggregator) -----


def _make_feeding_monitor_class():
    """Subclass the system MonitorMaster lazily (module-level import of
    system/ from telemetry/ would be a layering cycle): reports that
    the seq guard ACCEPTS are forwarded to the cluster aggregator;
    rejected redeliveries never reach it."""
    from ..system.monitor import MonitorMaster

    class _FeedingMonitorImpl(MonitorMaster):
        def __init__(self, cluster):
            # replace-merge (merger None): an export is cumulative
            # state, not a delta
            super().__init__()
            self._cluster = cluster  # set once; read-only afterwards

        def report(self, node_id, progress, seq=None) -> bool:
            merged = super().report(node_id, progress, seq=seq)
            if merged:
                self._cluster.update(node_id, progress)
            return merged

    return _FeedingMonitorImpl


_FeedingMonitorClass = None


def _FeedingMonitor(cluster):
    global _FeedingMonitorClass
    if _FeedingMonitorClass is None:
        _FeedingMonitorClass = _make_feeding_monitor_class()
    return _FeedingMonitorClass(cluster)


class ClusterFeedMaster:
    """Scheduler-side learning-progress master.

    A :class:`~..system.monitor.MonitorMaster` (typed, seq-guarded
    against redelivery) whose merged per-node payloads — each a plane's
    :meth:`LearningPlane.export` — are fed straight into the PR 10
    :class:`~.aggregate.ClusterAggregator`, so the next ``/metrics``
    scrape renders ``ps_learning_*`` under each node's label plus the
    cluster rollup. Duplicate reports the seq guard rejects never reach
    the aggregator (the redelivery contract, tier-1-tested)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self.monitor = _FeedingMonitor(cluster)

    def handle_message(self, msg) -> bool:
        return self.monitor.handle_message(msg)


def slaver_over_van(master: ClusterFeedMaster, node_id: str, van):
    """Node-side reporter for the learning plane: reports ride the real
    Van transfer path (serialization, byte accounting, the
    ``van.transfer`` fault point) into the feed master. Report with
    ``slaver.report(plane.export())`` or hang it on
    ``start_periodic(plane.export)``."""
    from ..system.monitor import MonitorSlaver

    return MonitorSlaver.over_van(master.monitor, node_id, van)
