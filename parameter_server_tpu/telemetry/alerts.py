"""Live SLO alerting: declarative rules evaluated against the registry.

PRs 6 and 9 made overload and failure *survivable* (admission 429s,
degraded 503s, recovery drills) but only *observable after the fact*,
by reading a bench record. This module closes the loop online: a small
Prometheus-alerting-style engine evaluates declarative threshold and
burn-rate rules on a sliding window of registry samples, walks each
rule through ``inactive → pending → firing → resolved``, exports the
state as ``ps_alert_state{rule=...}`` (0/1/2/3), and feeds every
transition to listeners (the Dashboard event log, via
``AuxRuntime.set_alerts``).

Rule kinds (``AlertRule.kind``):

- ``gauge`` — the metric's current value (max across matching series);
- ``counter_rate`` — per-second increase over the window (sum across
  matching series; counter resets clamp to no-data);
- ``ratio`` — rate(metric) / rate(sum of ``den`` metrics), e.g. the
  admission shed fraction shed/(shed+admitted);
- ``quantile`` — a WINDOWED histogram percentile from the bucket-count
  delta across the window (the registry's own percentile() is
  since-birth; alerting needs "p99 over the last 30s");
- ``burn_rate`` — ``ratio`` divided by the rule's error ``budget``:
  burn 1.0 consumes the budget exactly; sustained burn ≫ 1 pages;
- ``trend`` — a robust monotonic-slope test over a LONG window of the
  history plane (telemetry/history.py): Theil-Sen median slope gated
  by an up/down concordance fraction, for drift/leak rules (HBM
  high-water, live-buffer total, queue depth, staleness growth) that
  no instantaneous threshold can catch.

Multi-window conditions: ``counter_rate``/``ratio``/``burn_rate``/
``quantile`` rules with ``slow_window_s > 0`` evaluate from the
history plane over BOTH windows and the condition must hold on both —
the fast window catches a real overload quickly, the slow window keeps
a brief spike (shorter than the fast window's worth of budget) from
paging. Single-window rules keep the original in-process sample list.

A rule with no data (empty window, zero denominator, too few history
points) evaluates to None, which never satisfies the condition —
missing traffic resolves an alert rather than wedging it.

The default production rule set ships in ``configs/alerts/default.json``
(:func:`default_rules`); doc/OBSERVABILITY.md documents the syntax.
"""

from __future__ import annotations

import bisect
import dataclasses
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import registry as telemetry_registry

STATE_INACTIVE, STATE_PENDING, STATE_FIRING, STATE_RESOLVED = 0, 1, 2, 3
STATE_NAMES = {0: "inactive", 1: "pending", 2: "firing", 3: "resolved"}
KINDS = ("gauge", "counter_rate", "ratio", "quantile", "burn_rate", "trend")

#: kinds that may carry a slow window (fast+slow multi-window pairs)
_MULTI_WINDOW_KINDS = ("counter_rate", "ratio", "burn_rate", "quantile")
_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


@dataclasses.dataclass
class AlertRule:
    """One declarative rule (see module docstring for kinds)."""

    name: str
    kind: str
    metric: str
    threshold: float
    op: str = ">"
    labels: Optional[Dict[str, str]] = None  # None = all series
    den: Sequence[str] = ()      # ratio/burn_rate denominator metrics
    q: float = 0.99              # quantile kind
    budget: float = 0.0          # burn_rate error budget (fraction)
    window_s: float = 30.0       # sliding-window width (the FAST window)
    slow_window_s: float = 0.0   # > 0: multi-window pair, from history
    for_s: float = 0.0           # condition must hold this long to fire
    resolve_hold_s: float = 30.0  # how long 'resolved' shows before inactive
    min_points: int = 4          # trend: fewest history cells to judge
    monotonic_frac: float = 0.6  # trend: concordance gate (frac of steps)
    severity: str = "warn"       # page | warn (routing hint, not logic)
    description: str = ""

    def __post_init__(self) -> None:
        if self.kind not in KINDS:
            raise ValueError(f"rule {self.name!r}: unknown kind {self.kind!r}")
        if self.op not in _OPS:
            raise ValueError(f"rule {self.name!r}: unknown op {self.op!r}")
        if self.kind == "burn_rate" and self.budget <= 0:
            raise ValueError(f"rule {self.name!r}: burn_rate needs budget > 0")
        if self.kind in ("ratio", "burn_rate") and not self.den:
            raise ValueError(f"rule {self.name!r}: {self.kind} needs den=[...]")
        if not 0.0 < self.q < 1.0:
            raise ValueError(f"rule {self.name!r}: q outside (0, 1)")
        if self.slow_window_s:
            if self.kind not in _MULTI_WINDOW_KINDS:
                raise ValueError(
                    f"rule {self.name!r}: slow_window_s only applies to "
                    f"{_MULTI_WINDOW_KINDS}"
                )
            if self.slow_window_s <= self.window_s:
                raise ValueError(
                    f"rule {self.name!r}: slow_window_s "
                    f"({self.slow_window_s}) must exceed window_s "
                    f"({self.window_s})"
                )
        if self.kind == "trend":
            if self.min_points < 2:
                raise ValueError(
                    f"rule {self.name!r}: trend needs min_points >= 2"
                )
            if not 0.0 <= self.monotonic_frac <= 1.0:
                raise ValueError(
                    f"rule {self.name!r}: monotonic_frac outside [0, 1]"
                )


@dataclasses.dataclass
class AlertEvent:
    """One state transition, as delivered to listeners."""

    rule: str
    frm: str
    to: str
    value: Optional[float]
    threshold: float
    op: str
    t: float
    severity: str = "warn"

    def __str__(self) -> str:
        v = "n/a" if self.value is None else f"{self.value:.6g}"
        return (
            f"alert {self.rule}: {self.frm}->{self.to} "
            f"(value {v} {self.op} {self.threshold:g}, {self.severity})"
        )


class _RuleState:
    __slots__ = ("state", "value", "pending_since", "firing_since",
                 "resolved_at", "last_change")

    def __init__(self) -> None:
        self.state = STATE_INACTIVE
        self.value: Optional[float] = None
        self.pending_since: Optional[float] = None
        self.firing_since: Optional[float] = None
        self.resolved_at: Optional[float] = None
        self.last_change: Optional[float] = None

    @property
    def state_name(self) -> str:
        return STATE_NAMES[self.state]


# -- export readers (operate on MetricsRegistry.export_state dicts) --


def _series_matching(decl: dict, labels: Optional[Dict[str, str]]):
    for s in decl["series"]:
        if labels is None or all(
            str(s["labels"].get(k)) == str(v) for k, v in labels.items()
        ):
            yield s


def _scalar_sum(export: dict, metric: str, labels) -> Optional[float]:
    decl = export.get(metric)
    if decl is None:
        return None
    vals = [float(s["value"]) for s in _series_matching(decl, labels)]
    return sum(vals) if vals else None


def _scalar_max(export: dict, metric: str, labels) -> Optional[float]:
    decl = export.get(metric)
    if decl is None:
        return None
    vals = [float(s["value"]) for s in _series_matching(decl, labels)]
    return max(vals) if vals else None


def _hist_state(export: dict, metric: str, labels) -> Optional[Tuple[List[int], int]]:
    decl = export.get(metric)
    if decl is None or decl["type"] != "histogram":
        return None
    buckets: Optional[List[int]] = None
    count = 0
    for s in _series_matching(decl, labels):
        if buckets is None:
            buckets = [0] * len(s["buckets"])
        for i, c in enumerate(s["buckets"]):
            buckets[i] += int(c)
        count += int(s["count"])
    return None if buckets is None else (buckets, count)


def windowed_quantile(
    bounds: Sequence[float], dcounts: Sequence[int], dcount: int, q: float
) -> Optional[float]:
    """Percentile over a WINDOW of observations given the bucket-count
    delta across it. Same interpolation as the registry's percentile(),
    but bucket-edge-only (the window has no min/max): observations
    above the last finite bound clamp to it — fine for alerting, where
    the threshold sits well inside the bucket range."""
    if dcount <= 0:
        return None
    rank = q * dcount
    cum = 0.0
    for i, c in enumerate(dcounts):
        if c <= 0:
            continue
        lo = bounds[i - 1] if i else 0.0
        if cum + c >= rank:
            frac = (rank - cum) / c
            return lo + frac * (bounds[i] - lo)
        cum += c
    return float(bounds[-1])


class AlertManager:
    """Evaluates rules against sampled registry exports.

    ``evaluate()`` is driven either by the aux runtime's poll loop
    (``AuxRuntime.set_alerts``) or by :meth:`start`'s own timer thread;
    both may coexist — evaluation is idempotent per timestamp and
    cheap (one registry export per tick).
    """

    def __init__(
        self,
        rules: Sequence[AlertRule],
        registry=None,
        clock: Callable[[], float] = time.monotonic,
        history=None,
    ):
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.rules = list(rules)
        self._registry = registry  # None = resolve default at sample time
        self._clock = clock
        #: HistoryStore the trend / multi-window rules evaluate from;
        #: None resolves the process default (or lazily binds a private
        #: store when ``registry`` is private) at evaluate time
        self._history = history
        self._own_history = None
        #: expected evaluation period (seconds) — the baseline the
        #: ps_alert_eval_lag_seconds meta-gauge is judged against; set
        #: by :meth:`start` / the aux loop
        self.period_s = 1.0
        self._last_eval_t: Optional[float] = None  # guarded-by: _lock
        self._metrics = sorted(
            {r.metric for r in self.rules}
            | {m for r in self.rules for m in r.den}
        )
        # the sample list only serves single-window non-trend rules —
        # history-backed kinds must not inflate its retention
        self._window = max(
            (
                r.window_s for r in self.rules
                if r.kind != "trend" and not r.slow_window_s
            ),
            default=30.0,
        )
        self._samples: List[Tuple[float, dict]] = []  # guarded-by: _lock
        self._states: Dict[str, _RuleState] = {  # guarded-by: _lock
            r.name: _RuleState() for r in self.rules
        }
        self._events: List[AlertEvent] = []  # guarded-by: _lock
        self._listeners: List[Callable[[AlertEvent], None]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tel = None
        if telemetry_registry.enabled():
            from .instruments import alert_instruments

            self._tel = alert_instruments(
                telemetry_registry.default_registry()
            )

    def add_listener(self, fn: Callable[[AlertEvent], None]) -> None:
        self._listeners.append(fn)

    # -- sampling --

    def _sample(self) -> dict:
        reg = self._registry or telemetry_registry.default_registry()
        export = reg.export_state()
        # keep only the metrics rules reference — the deque holds
        # window_s worth of these every tick
        return {m: export[m] for m in self._metrics if m in export}

    # -- evaluation --

    def _history_store(self):
        """The HistoryStore backing trend / multi-window rules: the
        explicit one, the process default (tracks registry swaps), or a
        lazily-bound private store over an explicit private registry."""
        if self._history is not None:
            return self._history
        from . import history as history_mod

        if self._registry is None:
            return history_mod.default_store()
        if self._own_history is None:
            self._own_history = history_mod.HistoryStore(
                self._registry, clock=self._clock
            ).install()
        return self._own_history

    def evaluate(self, now: Optional[float] = None) -> List[AlertEvent]:
        """One tick: sample, compute every rule, advance state
        machines; returns (and delivers) the transitions."""
        now = self._clock() if now is None else now
        t_wall0 = time.perf_counter()
        # meta-monitoring BEFORE sampling, so the starvation rule reads
        # THIS tick's lag from this tick's own sample
        with self._lock:
            prev_t = self._last_eval_t
            self._last_eval_t = now
        if self._tel is not None and prev_t is not None:
            lag = max(0.0, (now - prev_t) - self.period_s)
            self._tel["eval_lag"].set(lag)
        # fold the history at this tick so history-backed rules see the
        # current registry state. The store folds and is queried on ITS
        # OWN clock (wall time for the process default; the evaluator's
        # clock may be monotonic — a different time base entirely), so
        # no explicit ``now`` is passed down. Fake-clock tests hand the
        # manager a HistoryStore built on the same fake clock.
        needs_history = any(
            r.kind == "trend" or r.slow_window_s for r in self.rules
        )
        if needs_history:
            try:
                self._history_store().fold()
            except Exception:
                pass  # a broken fold must not stop threshold alerting
        sample = self._sample()
        with self._lock:
            self._samples.append((now, sample))
            # drop samples older than the widest window (keep one
            # sample beyond the edge as the window's baseline)
            cutoff = now - self._window
            times = [t for t, _ in self._samples]
            keep_from = max(0, bisect.bisect_left(times, cutoff) - 1)
            del self._samples[:keep_from]
            samples = list(self._samples)
        events: List[AlertEvent] = []
        for rule in self.rules:
            value = self._compute(rule, samples, now)
            events.extend(self._advance(rule, value, now))
        for ev in events:
            with self._lock:
                self._events.append(ev)
                del self._events[:-64]
            for fn in list(self._listeners):
                try:
                    fn(ev)
                except Exception:
                    pass  # a broken listener must not stop alerting
        if self._tel is not None:
            self._tel["eval_seconds"].observe(
                time.perf_counter() - t_wall0
            )
        return events

    def _window_pair(
        self, rule: AlertRule, samples, now: float
    ) -> Optional[Tuple[Tuple[float, dict], Tuple[float, dict]]]:
        """(oldest-in-window, newest) sample pair, or None."""
        if not samples:
            return None
        cutoff = now - rule.window_s
        # baseline = the sample just BEFORE the cutoff when one exists
        # (the true window start), else the oldest sample available
        idx = 0
        for i, (t, _) in enumerate(samples):
            if t >= cutoff:
                idx = max(0, i - 1)
                break
        old = samples[idx]
        new = samples[-1]
        if new[0] <= old[0]:
            return None
        return old, new

    def _history_value(
        self, rule: AlertRule, window_s: float
    ) -> Optional[float]:
        """One window's value from the history plane (multi-window
        kinds): rates and quantiles computed from ring-cell deltas.
        Queries pass ``now=None`` so the store anchors the window on
        ITS OWN clock — the evaluator's clock may be a different time
        base (monotonic vs the default store's wall time)."""
        h = self._history_store()
        if rule.kind == "counter_rate":
            return h.window_rate(rule.metric, rule.labels, window_s)
        if rule.kind in ("ratio", "burn_rate"):
            num = h.window_rate(rule.metric, rule.labels, window_s)
            dens = [
                h.window_rate(m, rule.labels, window_s)
                for m in rule.den
            ]
            if num is None or any(d is None for d in dens):
                return None
            den = sum(dens)
            if den <= 0:
                return None
            value = num / den
            return value / rule.budget if rule.kind == "burn_rate" else value
        return h.window_quantile(
            rule.metric, rule.labels, window_s, rule.q
        )

    def _compute(
        self, rule: AlertRule, samples, now: float
    ) -> Optional[float]:
        if rule.kind == "trend":
            try:
                tr = self._history_store().trend(
                    rule.metric, rule.labels, rule.window_s,
                    min_points=rule.min_points,
                )
            except Exception:
                return None
            if tr is None:
                return None
            frac = (
                tr["frac_down"] if rule.op in ("<", "<=") else tr["frac_up"]
            )
            if frac < rule.monotonic_frac:
                return 0.0  # noise around a level, not a sustained drift
            return tr["slope_per_s"]
        if rule.slow_window_s:
            # fast AND slow must both breach: report the less-violating
            # window's value so the condition is the conjunction
            try:
                fast = self._history_value(rule, rule.window_s)
                slow = self._history_value(rule, rule.slow_window_s)
            except Exception:
                return None
            if fast is None or slow is None:
                return None
            pick = min if rule.op in (">", ">=") else max
            return pick(fast, slow)
        if rule.kind == "gauge":
            if not samples:
                return None
            return _scalar_max(samples[-1][1], rule.metric, rule.labels)
        pair = self._window_pair(rule, samples, now)
        if pair is None:
            return None
        (t0, s0), (t1, s1) = pair
        dt = t1 - t0

        def rate(metric: str) -> Optional[float]:
            v1 = _scalar_sum(s1, metric, rule.labels)
            if v1 is None:
                return None
            v0 = _scalar_sum(s0, metric, rule.labels)
            v0 = 0.0 if v0 is None else v0
            if v1 < v0:  # counter reset (registry swap): no safe delta
                return None
            return (v1 - v0) / dt

        if rule.kind == "counter_rate":
            return rate(rule.metric)
        if rule.kind in ("ratio", "burn_rate"):
            num = rate(rule.metric)
            dens = [rate(m) for m in rule.den]
            if num is None or any(d is None for d in dens):
                return None
            den = sum(dens)
            if den <= 0:
                return None
            value = num / den
            return value / rule.budget if rule.kind == "burn_rate" else value
        # quantile: bucket-count delta across the window
        h1 = _hist_state(s1, rule.metric, rule.labels)
        if h1 is None:
            return None
        h0 = _hist_state(s0, rule.metric, rule.labels)
        b0, c0 = h0 if h0 is not None else ([0] * len(h1[0]), 0)
        if len(b0) != len(h1[0]) or h1[1] < c0:
            return None  # bucket layout changed / reset
        dcounts = [a - b for a, b in zip(h1[0], b0)]
        reg = self._registry or telemetry_registry.default_registry()
        inst = reg.get(rule.metric)
        bounds = getattr(inst, "buckets", None)
        if bounds is None:
            return None
        return windowed_quantile(bounds, dcounts, h1[1] - c0, rule.q)

    def _advance(
        self, rule: AlertRule, value: Optional[float], now: float
    ) -> List[AlertEvent]:
        cond = value is not None and _OPS[rule.op](value, rule.threshold)
        events: List[AlertEvent] = []

        with self._lock:
            st = self._states[rule.name]
            st.value = value

            def goto(state: int) -> None:
                frm = st.state_name
                st.state = state
                st.last_change = now
                if state == STATE_PENDING:
                    st.pending_since = now
                elif state == STATE_FIRING:
                    st.firing_since = now
                elif state == STATE_RESOLVED:
                    st.resolved_at = now
                events.append(AlertEvent(
                    rule=rule.name, frm=frm, to=st.state_name, value=value,
                    threshold=rule.threshold, op=rule.op, t=now,
                    severity=rule.severity,
                ))

            if cond:
                if st.state in (STATE_INACTIVE, STATE_RESOLVED):
                    goto(STATE_PENDING)
                if (
                    st.state == STATE_PENDING
                    and now - st.pending_since >= rule.for_s
                ):
                    goto(STATE_FIRING)
            else:
                if st.state == STATE_FIRING:
                    goto(STATE_RESOLVED)
                elif st.state == STATE_PENDING:
                    # condition cleared before for_s elapsed: a flap,
                    # not a resolved incident
                    goto(STATE_INACTIVE)
                elif (
                    st.state == STATE_RESOLVED
                    and now - st.resolved_at >= rule.resolve_hold_s
                ):
                    goto(STATE_INACTIVE)
            state_now = st.state

        if self._tel is not None:
            self._tel["state"].labels(rule=rule.name).set(state_now)
            for ev in events:
                self._tel["transitions"].labels(
                    rule=rule.name, to=ev.to
                ).inc()
        return events

    # -- reads --

    def states(self) -> Dict[str, _RuleState]:
        with self._lock:
            return dict(self._states)

    def firing(self) -> List[str]:
        with self._lock:
            return sorted(
                n for n, st in self._states.items()
                if st.state == STATE_FIRING
            )

    def events(self, n: int = 64) -> List[AlertEvent]:
        with self._lock:
            return list(self._events[-n:])

    def snapshot(self) -> dict:
        """JSON view for /debug/snapshot."""
        with self._lock:
            states = {
                name: {
                    "state": st.state,
                    "state_name": st.state_name,
                    "value": st.value,
                    "since": st.last_change,
                }
                for name, st in sorted(self._states.items())
            }
            events = [dataclasses.asdict(e) for e in self._events[-16:]]
        return {
            "rules": [dataclasses.asdict(r) for r in self.rules],
            "states": states,
            "recent_events": events,
        }

    # -- standalone timer (expose_cluster uses the aux loop instead) --

    def start(self, interval: float = 1.0) -> "AlertManager":
        if self._thread is not None:
            return self
        self.period_s = float(interval)
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval):
                try:
                    self.evaluate()
                except Exception:
                    pass  # never kill the evaluator thread

        self._thread = threading.Thread(
            target=loop, daemon=True, name="alert-evaluator"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None


# -- rule files (configs/alerts/*.json) --

_RULE_FIELDS = {f.name for f in dataclasses.fields(AlertRule)}


def load_rules(path: str) -> List[AlertRule]:
    """Parse a rule file: ``{"version": 1, "rules": [{...}, ...]}``;
    unknown keys are an error (a typo'd field must not silently relax a
    production rule)."""
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != 1:
        raise ValueError(f"{path}: unsupported rule-file version "
                         f"{doc.get('version')!r}")
    rules = []
    for entry in doc["rules"]:
        unknown = set(entry) - _RULE_FIELDS
        if unknown:
            raise ValueError(
                f"{path}: rule {entry.get('name', '?')!r} has unknown "
                f"fields {sorted(unknown)}"
            )
        rules.append(AlertRule(**entry))
    return rules


def default_rules_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        "configs", "alerts", "default.json",
    )


def default_rules() -> List[AlertRule]:
    """The shipped production rule set (configs/alerts/default.json):
    serve p99 vs SLO, degraded-serve rate, admission shed burn rate,
    serve queue depth, recovery MTTR."""
    return load_rules(default_rules_path())
