"""Unified telemetry: metrics registry + logical-clock span tracing.

One low-overhead spine for every layer's observability (see
``doc/OBSERVABILITY.md`` for the metric catalog and how to read it):

- :mod:`registry` — named Counter/Gauge/Histogram instruments,
  process-default registry (hung off ``Postoffice``), JSON snapshots and
  Prometheus text exposition;
- :mod:`spans` — ``span(name, ts=...)`` host intervals correlated to
  executor logical timestamps, appended to a JSONL sink; flow ids
  (``new_flow``/``flow_scope``) correlate one batch/request across
  threads;
- :mod:`timeline` — merged cross-thread timeline reader + Chrome
  trace-event / Perfetto export with flow arrows;
- :mod:`attribution` — critical-path analyzer over a timeline: per-step
  / per-request attribution to {host-prep, encode, upload, queue-wait,
  device-compute, decode, reply} and the binding resource;
- :mod:`instruments` — the canonical catalog of metric names each layer
  records (executor phases, van bytes, parameter push/pull, app volume,
  heartbeat traffic);
- :mod:`aggregate` — cluster aggregation: per-node registry exports
  merged under a ``node`` label (counters sum, gauges stay per-node,
  histograms merge bucket-wise) with per-node staleness marking;
- :mod:`exposition` — the HTTP scrape point (/metrics, /healthz,
  /debug/snapshot) over the cluster aggregate;
- :mod:`alerts` — declarative threshold/burn-rate SLO rules evaluated
  in-process on a sliding window, pending→firing→resolved state
  exported as ``ps_alert_state``; multi-window (fast+slow burn) and
  ``trend`` (drift/leak) conditions evaluate from the history plane;
- :mod:`history` — the time plane: a bounded multi-resolution ring
  cascade (1 s × 10 m → 10 s × 2 h → 60 s × 12 h) over the registry
  with typed downsampling (counters→rate deltas, gauges→last/min/max,
  histograms→bucket-delta merges), range queries, robust trend
  estimation and steady-state drift checks
  (``doc/OBSERVABILITY.md`` "History plane");
- :mod:`device` — the device truth plane: a compiled-function
  inventory over the jit entry points (per-name cost/memory analysis,
  recompile detection, runtime donation-aliasing verification), live
  roofline gauges, and HBM/live-buffer accounting
  (``doc/OBSERVABILITY.md`` "Device truth plane").
"""

from .aggregate import CLUSTER_NODE, ClusterAggregator
from .alerts import AlertManager, AlertRule, default_rules, load_rules
from .device import DeviceInventory, HbmMonitor, aot_analyze, instrument
from .exposition import ExpositionServer, close_cluster, expose_cluster
from .history import (
    HistoryStore,
    default_store,
    drift_check,
    installed_store,
    reset_default_store,
    set_default_store,
)

from .registry import (
    Counter,
    DuplicateMetricError,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    enabled,
    reset_default_registry,
    set_enabled,
)
from .spans import (
    JsonlSink,
    close_sink,
    current_flow,
    emit,
    flow_scope,
    get_sink,
    install_sink,
    maybe_new_flow,
    new_flow,
    parked_sink,
    span,
)

__all__ = [
    "AlertManager",
    "AlertRule",
    "CLUSTER_NODE",
    "ClusterAggregator",
    "Counter",
    "DeviceInventory",
    "DuplicateMetricError",
    "ExpositionServer",
    "Gauge",
    "HbmMonitor",
    "HistoryStore",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "aot_analyze",
    "close_cluster",
    "default_rules",
    "default_store",
    "drift_check",
    "expose_cluster",
    "installed_store",
    "reset_default_store",
    "set_default_store",
    "instrument",
    "load_rules",
    "close_sink",
    "current_flow",
    "default_registry",
    "emit",
    "enabled",
    "flow_scope",
    "get_sink",
    "install_sink",
    "maybe_new_flow",
    "new_flow",
    "parked_sink",
    "reset_default_registry",
    "set_enabled",
    "span",
]
