"""Unified telemetry: metrics registry + logical-clock span tracing.

One low-overhead spine for every layer's observability (see
``doc/OBSERVABILITY.md`` for the metric catalog and how to read it):

- :mod:`registry` — named Counter/Gauge/Histogram instruments,
  process-default registry (hung off ``Postoffice``), JSON snapshots and
  Prometheus text exposition;
- :mod:`spans` — ``span(name, ts=...)`` host intervals correlated to
  executor logical timestamps, appended to a JSONL sink;
- :mod:`instruments` — the canonical catalog of metric names each layer
  records (executor phases, van bytes, parameter push/pull, app volume,
  heartbeat traffic).
"""

from .registry import (
    Counter,
    DuplicateMetricError,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    enabled,
    reset_default_registry,
    set_enabled,
)
from .spans import JsonlSink, close_sink, emit, get_sink, install_sink, span

__all__ = [
    "Counter",
    "DuplicateMetricError",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "close_sink",
    "default_registry",
    "emit",
    "enabled",
    "get_sink",
    "install_sink",
    "reset_default_registry",
    "set_enabled",
    "span",
]
