"""Unified telemetry: metrics registry + logical-clock span tracing.

One low-overhead spine for every layer's observability (see
``doc/OBSERVABILITY.md`` for the metric catalog and how to read it):

- :mod:`registry` — named Counter/Gauge/Histogram instruments,
  process-default registry (hung off ``Postoffice``), JSON snapshots and
  Prometheus text exposition;
- :mod:`spans` — ``span(name, ts=...)`` host intervals correlated to
  executor logical timestamps, appended to a JSONL sink; flow ids
  (``new_flow``/``flow_scope``) correlate one batch/request across
  threads;
- :mod:`timeline` — merged cross-thread timeline reader + Chrome
  trace-event / Perfetto export with flow arrows;
- :mod:`attribution` — critical-path analyzer over a timeline: per-step
  / per-request attribution to {host-prep, encode, upload, queue-wait,
  device-compute, decode, reply} and the binding resource;
- :mod:`instruments` — the canonical catalog of metric names each layer
  records (executor phases, van bytes, parameter push/pull, app volume,
  heartbeat traffic).
"""

from .registry import (
    Counter,
    DuplicateMetricError,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    enabled,
    reset_default_registry,
    set_enabled,
)
from .spans import (
    JsonlSink,
    close_sink,
    current_flow,
    emit,
    flow_scope,
    get_sink,
    install_sink,
    maybe_new_flow,
    new_flow,
    parked_sink,
    span,
)

__all__ = [
    "Counter",
    "DuplicateMetricError",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "close_sink",
    "current_flow",
    "default_registry",
    "emit",
    "enabled",
    "flow_scope",
    "get_sink",
    "install_sink",
    "maybe_new_flow",
    "new_flow",
    "parked_sink",
    "reset_default_registry",
    "set_enabled",
    "span",
]
