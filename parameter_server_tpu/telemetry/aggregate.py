"""Cluster aggregation: node-labeled merges of registry exports.

The reference ships cluster health as a first-class feature — heartbeat
reports flow over messages into a scheduler-side dashboard and
MonitorMaster merges per-node progress on a timer (``src/system/
monitor.h`` + ``dashboard.cc``). This module is the registry-level
version of that merge: every node periodically ships its registry's
raw state (:meth:`MetricsRegistry.export_state` — plain dicts, so the
report survives the restricted wire unpickler), and the scheduler-side
:class:`ClusterAggregator` folds the exports into one view where every
series carries a ``node`` label.

Typed merge semantics (doc/OBSERVABILITY.md "Cluster metrics plane"):

- **counters sum** — each node's series is kept under its ``node``
  label AND a ``node="cluster"`` rollup carries the sum per inner
  label set;
- **gauges keep per-node series** — a point-in-time value summed
  across nodes means nothing, so gauges get no rollup;
- **histograms merge bucket-wise** — exports carry raw bucket counts
  (not percentiles), so the cluster rollup is the element-wise sum of
  bucket counts + count/sum, with min/max folded; nodes whose bucket
  bounds disagree with the first-seen declaration are a merge
  CONFLICT (counted, per-node series skipped) rather than a silent
  mis-merge.

Staleness: each node's last-report time is tracked; a node silent for
longer than ``stale_after_s`` is *marked* in the merged view
(``ps_cluster_node_up{node=...} 0`` + its report age) instead of its
last values silently freezing — the difference between "the shard is
fine" and "the scraper is reading a corpse".
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from . import registry as telemetry_registry
from .registry import (
    MetricsRegistry,
    _escape,
    _fmt,
    _help_line,
    _histogram_lines,
)

#: the ``node`` label value carried by merged (cluster-rollup) series —
#: reserved: a real node reporting under this id is rejected
CLUSTER_NODE = "cluster"

#: the label the aggregator prepends to every merged series
NODE_LABEL = "node"


def export_default_registry() -> Dict[str, dict]:
    """The process default registry's raw export (one node's report)."""
    return telemetry_registry.default_registry().export_state()


def _series_key(labels: Dict[str, str], labelnames: List[str]) -> Tuple[str, ...]:
    return tuple(str(labels.get(n, "")) for n in labelnames)


def _prom_labels(pairs: List[Tuple[str, str]], extra: str = "") -> str:
    parts = [f'{n}="{_escape(str(v))}"' for n, v in pairs]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _MergedHist:
    __slots__ = ("buckets", "count", "sum", "min", "max")

    def __init__(self, nbuckets: int):
        self.buckets = [0] * nbuckets
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def fold(self, series: dict) -> None:
        for i, c in enumerate(series["buckets"]):
            self.buckets[i] += int(c)
        self.count += int(series["count"])
        self.sum += float(series["sum"])
        for attr, pick in (("min", min), ("max", max)):
            v = series.get(attr)
            if v is None:
                continue
            cur = getattr(self, attr)
            setattr(self, attr, float(v) if cur is None else pick(cur, float(v)))


class ClusterAggregator:
    """node id → latest registry export, merged under a ``node`` label.

    Thread-safe: reports arrive from the aux runtime's timer thread (or
    straight off a Van transfer) while the exposition endpoint renders.
    Rendering snapshots under the lock and formats outside it.
    """

    def __init__(
        self,
        stale_after_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.stale_after_s = float(stale_after_s)
        self._clock = clock
        self._exports: Dict[str, Dict[str, dict]] = {}  # guarded-by: _lock
        self._last_t: Dict[str, float] = {}  # guarded-by: _lock
        self._reports: Dict[str, int] = {}  # guarded-by: _lock
        # per-node down-sampled history rings (telemetry/history.py
        # export_ring shape) with their own arrival times — histories
        # are PER-NODE evidence: they are never folded into the
        # node="cluster" rollup (range queries disclose each node's
        # ring and its staleness instead)
        self._histories: Dict[str, dict] = {}  # guarded-by: _lock
        self._history_t: Dict[str, float] = {}  # guarded-by: _lock
        # distinct (node, metric) pairs ever rejected from the merge —
        # a SET so one persistently-bad export counts once, not once
        # per scrape (merged() runs at the scrape rate)
        self._conflict_keys: set = set()  # guarded-by: _lock
        self._lock = threading.Lock()

    # -- ingest --

    def update(
        self, node: str, export: Dict[str, dict], t: Optional[float] = None
    ) -> None:
        """Fold one node's report in (replaces the node's previous
        export wholesale — exports are cumulative state, not deltas)."""
        if node == CLUSTER_NODE:
            raise ValueError(
                f"node id {CLUSTER_NODE!r} is reserved for merged series"
            )
        t = self._clock() if t is None else t
        with self._lock:
            self._exports[node] = export
            self._last_t[node] = t
            self._reports[node] = self._reports.get(node, 0) + 1

    def update_history(
        self, node: str, ring: dict, t: Optional[float] = None
    ) -> None:
        """Fold one node's shipped history ring in (wholesale replace,
        like :meth:`update` — rings are self-contained dumps). A report
        frame that arrives WITHOUT a ring leaves the previous one in
        place untouched: its age keeps growing, so a torn shipment
        shows as staleness, never as a poisoned or vanished ring."""
        if node == CLUSTER_NODE:
            raise ValueError(
                f"node id {CLUSTER_NODE!r} is reserved for merged series"
            )
        t = self._clock() if t is None else t
        with self._lock:
            self._histories[node] = ring
            self._history_t[node] = t

    def history_ages(self, now: Optional[float] = None) -> Dict[str, float]:
        now = self._clock() if now is None else now
        with self._lock:
            return {n: now - t for n, t in self._history_t.items()}

    def history_query(
        self,
        name: str,
        labels: Optional[Dict[str, str]] = None,
        window_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> dict:
        """Fleet-wide range query over the shipped per-node rings:
        node-keyed series for one metric, each node carrying its ring
        age and staleness verdict. A stale node's last ring is still
        DISCLOSED (it is evidence) but flagged — and no cross-node
        rollup exists to silently absorb it."""
        ages = self.history_ages(now)
        with self._lock:
            rings = dict(self._histories)
        out: Dict[str, dict] = {"name": name, "nodes": {}}
        for node in sorted(rings):
            ring = rings[node]
            age = ages.get(node, -1.0)
            entry: dict = {
                "age_s": round(age, 3),
                "stale": age > self.stale_after_s,
                "ring_t": ring.get("t"),
            }
            decl = ring.get("metrics", {}).get(name)
            if decl is not None:
                series = [
                    s for s in decl.get("series", ())
                    if labels is None or all(
                        str(s.get("labels", {}).get(k)) == str(v)
                        for k, v in labels.items()
                    )
                ]
                if window_s is not None and ring.get("t") is not None:
                    cutoff = float(ring["t"]) - float(window_s)
                    series = [
                        {
                            **s,
                            "points": [
                                p for p in s.get("points", ())
                                if p.get("t", 0.0) >= cutoff
                            ],
                        }
                        for s in series
                    ]
                entry["kind"] = decl.get("kind")
                entry["resolution"] = decl.get("resolution")
                entry["series"] = series
            out["nodes"][node] = entry
        return out

    def history_snapshot(self, now: Optional[float] = None) -> dict:
        """Per-node ring occupancy + staleness (/debug/snapshot)."""
        ages = self.history_ages(now)
        with self._lock:
            rings = dict(self._histories)
        return {
            "stale_after_s": self.stale_after_s,
            "nodes": {
                n: {
                    "age_s": round(ages.get(n, -1.0), 3),
                    "stale": ages.get(n, 0.0) > self.stale_after_s,
                    "series": rings[n].get("series"),
                    "window_s": rings[n].get("window_s"),
                    "metrics": len(rings[n].get("metrics", {})),
                }
                for n in sorted(rings)
            },
        }

    def forget(self, node: str) -> None:
        """Drop a decommissioned node (elastic shrink — a node removed
        on purpose must not linger as 'stale' forever)."""
        with self._lock:
            self._exports.pop(node, None)
            self._last_t.pop(node, None)
            self._reports.pop(node, None)
            self._histories.pop(node, None)
            self._history_t.pop(node, None)

    # -- staleness --

    def node_ages(self, now: Optional[float] = None) -> Dict[str, float]:
        now = self._clock() if now is None else now
        with self._lock:
            return {n: now - t for n, t in self._last_t.items()}

    def stale_nodes(self, now: Optional[float] = None) -> List[str]:
        return sorted(
            n
            for n, age in self.node_ages(now).items()
            if age > self.stale_after_s
        )

    @property
    def conflicts(self) -> int:
        """Distinct (node, metric) merge rejections seen so far."""
        with self._lock:
            return len(self._conflict_keys)

    # -- merge --

    def merged(self) -> Dict[str, dict]:
        """The cluster view in export_state shape: every series gains a
        ``node`` label; counters and histograms additionally carry a
        ``node="cluster"`` rollup series. JSON-able (/debug/snapshot)."""
        with self._lock:
            exports = {n: e for n, e in self._exports.items()}
        out: Dict[str, dict] = {}
        conflicts = set()
        for node in sorted(exports):
            for name in sorted(exports[node]):
                decl = exports[node][name]
                ref = out.get(name)
                if ref is None:
                    ref = out[name] = {
                        "type": decl["type"],
                        "help": decl.get("help", ""),
                        "labelnames": [NODE_LABEL] + list(decl["labelnames"]),
                        "series": [],
                    }
                    if decl["type"] == "histogram":
                        ref["buckets"] = list(decl["buckets"])
                elif ref["type"] != decl["type"] or (
                    decl["type"] == "histogram"
                    and list(decl["buckets"]) != ref["buckets"]
                ):
                    # a node re-declared the name with a different kind
                    # or bucket layout — merging would be a lie; count
                    # it and keep that node's series out
                    conflicts.add((node, name))
                    continue
                for s in decl["series"]:
                    labeled = dict(s)
                    labeled["labels"] = {NODE_LABEL: node, **s["labels"]}
                    ref["series"].append(labeled)
        if conflicts:
            with self._lock:
                self._conflict_keys |= conflicts
        # rollups: counters sum, histograms merge bucket-wise; gauges
        # keep per-node series only
        for name, decl in out.items():
            inner = decl["labelnames"][1:]
            if decl["type"] == "counter":
                sums: Dict[Tuple[str, ...], float] = {}
                for s in decl["series"]:
                    k = _series_key(s["labels"], inner)
                    sums[k] = sums.get(k, 0.0) + float(s["value"])
                for k in sorted(sums):
                    decl["series"].append({
                        "labels": {
                            NODE_LABEL: CLUSTER_NODE,
                            **dict(zip(inner, k)),
                        },
                        "value": sums[k],
                    })
            elif decl["type"] == "histogram":
                folds: Dict[Tuple[str, ...], _MergedHist] = {}
                for s in decl["series"]:
                    k = _series_key(s["labels"], inner)
                    h = folds.get(k)
                    if h is None:
                        h = folds[k] = _MergedHist(len(decl["buckets"]))
                    h.fold(s)
                for k in sorted(folds):
                    h = folds[k]
                    decl["series"].append({
                        "labels": {
                            NODE_LABEL: CLUSTER_NODE,
                            **dict(zip(inner, k)),
                        },
                        "buckets": list(h.buckets),
                        "count": h.count,
                        "sum": h.sum,
                        "min": h.min,
                        "max": h.max,
                    })
        return out

    # -- render --

    def _meta_registry(self, now: Optional[float] = None) -> MetricsRegistry:
        """The aggregator's own health series (ps_cluster_*), built
        against a fresh registry at render time — names declared in the
        canonical catalog (telemetry/instruments.py cluster_instruments)
        so the metrics lint covers them like every other family."""
        from .instruments import cluster_instruments

        reg = MetricsRegistry()
        tel = cluster_instruments(reg)
        ages = self.node_ages(now)
        with self._lock:
            reports = dict(self._reports)
            conflicts = len(self._conflict_keys)
        tel["nodes"].set(len(ages))
        if conflicts:
            tel["conflicts"].inc(conflicts)
        for node, age in sorted(ages.items()):
            tel["node_up"].labels(node=node).set(
                0.0 if age > self.stale_after_s else 1.0
            )
            tel["report_age"].labels(node=node).set(age)
            tel["reports"].labels(node=node).inc(reports.get(node, 0))
        return reg

    def render_text(self, now: Optional[float] = None) -> str:
        """Prometheus text of the merged, node-labeled view, prefixed by
        the aggregator's own ps_cluster_* health series (node up/age —
        the staleness marking). Merge runs FIRST so conflicts detected
        in this scrape already show in this scrape's meta block."""
        merged = self.merged()
        lines: List[str] = [self._meta_registry(now).render_text().rstrip("\n")]
        for name in sorted(merged):
            decl = merged[name]
            if decl["help"]:
                lines.append(_help_line(name, decl["help"]))
            lines.append(f"# TYPE {name} {decl['type']}")
            inner = decl["labelnames"]
            for s in decl["series"]:
                pairs = [(n, s["labels"].get(n, "")) for n in inner]
                if decl["type"] == "histogram":
                    # the ONE histogram text renderer, shared with the
                    # live registry (registry._histogram_lines) so the
                    # two /metrics producers cannot drift
                    lines.extend(_histogram_lines(
                        name,
                        lambda extra, pairs=pairs: _prom_labels(pairs, extra),
                        decl["buckets"], s["buckets"], s["count"], s["sum"],
                    ))
                else:
                    lines.append(
                        f"{name}{_prom_labels(pairs)} {_fmt(s['value'])}"
                    )
        return "\n".join(lines) + "\n"

    def snapshot(self, now: Optional[float] = None) -> dict:
        """JSON view for /debug/snapshot: node ages + staleness verdicts
        + the merged export."""
        now = self._clock() if now is None else now
        ages = self.node_ages(now)
        with self._lock:
            reports = dict(self._reports)
        return {
            "stale_after_s": self.stale_after_s,
            "nodes": {
                n: {
                    "report_age_s": round(age, 3),
                    "stale": age > self.stale_after_s,
                    "reports": reports.get(n, 0),
                }
                for n, age in sorted(ages.items())
            },
            "merge_conflicts": self.conflicts,
            "merged": self.merged(),
        }
