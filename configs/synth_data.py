#!/usr/bin/env python
"""Generate synthetic libsvm/criteo-format shards so every config under
configs/ can run without network access (the reference's download.sh
scripts need egress; this is the offline stand-in).

    python configs/synth_data.py rcv1    # data/rcv1/{train,test}/part-*
    python configs/synth_data.py criteo  # data/criteo/{train,test}/part.*
    python configs/synth_data.py ctr     # data/ctr/{train,test}/part-*

Labels follow a sparse ground-truth weight vector so the solvers have
signal to converge on (same trick as tests/test_async_sgd.py).
"""

from __future__ import annotations

import os
import sys

import numpy as np


def _rows(rng, n, p, nnz, w):
    idx = rng.integers(0, p, size=(n, nnz))
    y = np.where(w[idx].sum(axis=1) > 0, 1, -1)
    return y, idx


def write_libsvm(path: str, rng, n: int, p: int, nnz: int, w) -> None:
    y, idx = _rows(rng, n, p, nnz, w)
    with open(path, "w") as f:
        for i in range(n):
            feats = " ".join(f"{j}:1" for j in sorted(set(idx[i].tolist())))
            f.write(f"{y[i]} {feats}\n")


def write_ps_sparse_binary(path: str, rng, n: int, p: int, nnz: int, w) -> None:
    """ps SPARSE_BINARY text: "label; grp key key ...;" (the ctr-data
    sample's format)."""
    y, idx = _rows(rng, n, p, nnz, w)
    with open(path, "w") as f:
        for i in range(n):
            keys = " ".join(str(j) for j in sorted(set(idx[i].tolist())))
            f.write(f"{1 if y[i] > 0 else 0}; 0 {keys};\n")


def write_criteo(path: str, rng, n: int, p: int, w) -> None:
    y, idx = _rows(rng, n, p, 26, w)
    ints = rng.integers(0, 100, size=(n, 13))
    with open(path, "w") as f:
        for i in range(n):
            label = 1 if y[i] > 0 else 0
            num = "\t".join(str(v) for v in ints[i])
            cat = "\t".join(f"{v:08x}" for v in idx[i])
            f.write(f"{label}\t{num}\t{cat}\n")


def main() -> int:
    name = sys.argv[1] if len(sys.argv) > 1 else "rcv1"
    shards = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    rows = int(sys.argv[3]) if len(sys.argv) > 3 else 5000
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "data")
    rng = np.random.default_rng(0)
    p = 1 << 16
    w = (rng.normal(size=p) * (rng.random(p) < 0.1)).astype(np.float32)
    for split in ("train", "test"):
        d = os.path.join(root, name, split)
        os.makedirs(d, exist_ok=True)
        for s in range(shards):
            # criteo configs match "part.*", libsvm ones "part-*": use a
            # name both globs accept
            part = os.path.join(d, f"part-{s + 1:03d}")
            if name == "criteo":
                part = os.path.join(d, f"part.{s + 1:03d}")
                write_criteo(part, rng, rows, p, w)
            elif name == "ctr":
                write_ps_sparse_binary(part, rng, rows, p, 32, w)
            else:
                write_libsvm(part, rng, rows, p, 32, w)
        print(f"wrote {shards} shards under {d}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
