#!/bin/bash
# Fetch the sample CTR dataset (ref example/linear/ctr/download.sh).
set -e
dir=$(dirname "$0")
git clone https://github.com/mli/ctr-data "$dir/../../data/ctr"
