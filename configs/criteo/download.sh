#!/bin/bash
# Criteo Display Advertising Challenge data (ref example/linear/criteo/
# download.sh pointed at the now-retired criteolabs URL; fetch the
# kaggle/criteo terabyte-sample from your mirror of choice), then shard:
#   split -n l/16 train.txt data/criteo/train/part-
set -e
echo "Place criteo train.txt/test.txt under data/criteo/ and shard with split(1)."
echo "The original criteolabs download URL has been retired; see"
echo "https://ailab.criteo.com/ressources/ for current hosting."
exit 1
