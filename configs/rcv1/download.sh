#!/bin/bash
# Fetch + shard the rcv1 binary-classification dataset (ref
# example/linear/rcv1/download.sh): 8 libsvm part files per split under
# data/rcv1/{train,test}. Needs network; for offline smoke data use
# ../synth_data.py instead.
set -e
dir=$(dirname "$0")
mkdir -p "$dir/../../data" && cd "$dir/../../data"

for t in train test; do
  if ! [ -e rcv1_${t}.binary ]; then
    wget http://www.csie.ntu.edu.tw/~cjlin/libsvmtools/datasets/binary/rcv1_${t}.binary.bz2
    bunzip2 rcv1_${t}.binary.bz2
  fi
  rnd=rcv1_${t}_rand
  shuf rcv1_${t}.binary > $rnd
  mkdir -p rcv1/${t}
  rm -f rcv1/${t}/*
  split -n l/8 --numeric-suffixes=1 --suffix-length=3 $rnd rcv1/${t}/part-
  rm $rnd
done
# the reference swaps splits so "train" is the bigger file set
mv rcv1/train tmp && mv rcv1/test rcv1/train && mv tmp rcv1/test
